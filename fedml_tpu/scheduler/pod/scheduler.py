"""PodScheduler — the dispatch loop over queue + allocator + resources.

One pass (`step()`) reaps exits, applies cancel/preempt requests, drives
drains to their grace deadline, asks the `GangAllocator` for a placement
plan, and dispatches.  `start()` runs the same pass on a background
thread (the `fedml jobs pod` daemon); tests call `step()` synchronously.

Preemption lifecycle (the "nearly free" path the PR-4 checkpoints buy):

    RUNNING ──drain()──► PREEMPTING ──exit 75──► QUEUED (resume=1)
       │                     │                      │
       │                     └─grace exceeded──► kill() → same requeue
       └─exit 0 during drain──► FINISHED (it just finished first)

The drained server force-saves its `RoundCheckpointer` state at the next
round boundary before exiting, so the requeued dispatch's
``--resume-from latest`` loses zero rounds and re-counts zero uploads.

Elastic resize rides the same round-boundary machinery without the
requeue round-trip (docs/SCHEDULER.md "Elastic resize"):

    RUNNING ──resize file──► workload checkpoints, re-meshes IN PLACE
       │                         │
       │                         ├─ack ok──► still RUNNING at new size
       │                         └─ack failed / grace / death──►
       │                              fallback: drain → exit 75 → requeue
       └─(the fallback ladder: resize → preempt → kill)

A grow pre-allocates the extra slots in the resource db under the job's
run_id before the announce, so backfill can't steal them mid-resize; a
shrink releases the excess only after the workload acks — the slots stay
pinned until the re-mesh is real.

Queue metrics exported from here: ``fedml_job_queue_wait_seconds``,
``fedml_pod_slot_utilization``, ``fedml_jobs_preempted_total``,
``fedml_pod_resizes_total``, ``fedml_resize_downtime_seconds`` plus
depth/running/eviction series.
"""

from __future__ import annotations

import logging
import os
import threading
import time
import uuid
from typing import Any, Dict, List, Optional

from ...core.mlops import ledger, metrics
from ...core.mlops.lock_profiler import named_lock
from ..resource_db import ComputeResourceDB
from .allocator import GangAllocator
from .jobspec import PREEMPTED_EXIT_CODE, JobState
from .queue import JobQueue
from .runners import (JobContext, SubprocessJobRunner, clear_resize,
                      read_resize_ack, signal_resize)

_queue_wait = metrics.histogram(
    "fedml_job_queue_wait_seconds",
    "Time a job spent QUEUED before its gang was dispatched",
    labels=("tenant",),
    buckets=(0.1, 0.5, 1.0, 5.0, 15.0, 60.0, 300.0, 1800.0, 7200.0))
_slot_util = metrics.gauge(
    "fedml_pod_slot_utilization",
    "Fraction of pod device slots currently allocated to jobs")
_preempted_total = metrics.counter(
    "fedml_jobs_preempted_total",
    "Jobs preempted at a round boundary and requeued with resume",
    labels=("tenant",))
_evictions_total = metrics.counter(
    "fedml_pod_evictions_total",
    "Preemptions initiated by the allocator for higher-priority jobs",
    labels=("tenant",))
_queue_depth = metrics.gauge(
    "fedml_pod_queue_depth", "Jobs waiting in the QUEUED state")
_jobs_running = metrics.gauge(
    "fedml_pod_jobs_running", "Jobs currently dispatched on the pod")
_resizes_total = metrics.counter(
    "fedml_pod_resizes_total",
    "Round-boundary gang resizes by direction and outcome "
    "(ok = completed in place, fallback = degraded to preempt/resume)",
    labels=("direction", "outcome"))
_resize_downtime = metrics.histogram(
    "fedml_resize_downtime_seconds",
    "Checkpoint -> re-mesh -> resume pause of an in-place resize",
    buckets=(0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 15.0, 60.0))


class PodScheduler:
    def __init__(self, queue: JobQueue, resources: ComputeResourceDB,
                 runner: Optional[Any] = None,
                 tenant_weights: Optional[Dict[str, float]] = None,
                 tick_s: float = 0.5, drain_grace_s: float = 60.0,
                 resize_grace_s: float = 60.0,
                 serving_scaler: Optional[Any] = None) -> None:
        self.queue = queue
        self.resources = resources
        self.runner = runner or SubprocessJobRunner()
        self.allocator = GangAllocator(tenant_weights)
        self.tick_s = float(tick_s)
        self.drain_grace_s = float(drain_grace_s)
        self.resize_grace_s = float(resize_grace_s)
        self.serving_scaler = serving_scaler
        self.aot_cache_dir = os.path.join(queue.root, "aot_cache")
        self._lock = named_lock("PodScheduler._lock")
        self._handles: Dict[str, Any] = {}
        self._reservations: Dict[str, int] = {}
        self._drain_started: Dict[str, float] = {}
        #: job_id → in-flight resize state ({"t0", "from", "to",
        #: "run_id", "path", "slots_after", "extra"})
        self._resizes: Dict[str, Dict[str, Any]] = {}
        self._busy_slot_seconds = 0.0
        self._t0: Optional[float] = None
        self._last_tick: Optional[float] = None
        self._last_in_use = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle ------------------------------------------------------------
    def start(self) -> "PodScheduler":
        # fresh event per start: a rebind, not a cross-thread mutation —
        # stop() always signals the event the live loop is waiting on
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="pod-scheduler")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None

    def _loop(self) -> None:
        while not self._stop.wait(self.tick_s):
            try:
                self.step()
            except Exception:  # noqa: BLE001 — one bad pass must not
                # kill the daemon; the queue state is re-read every tick
                logging.exception("pod: scheduler pass failed")

    # -- accounting -----------------------------------------------------------
    def aggregate_utilization(self) -> float:
        """Busy slot-seconds / (total slots × elapsed) since the first
        step — the soak's headline number."""
        total = int(self.resources.report()["total"]) or 1
        with self._lock:
            if self._t0 is None or self._last_tick is None:
                return 0.0
            elapsed = self._last_tick - self._t0
            busy = self._busy_slot_seconds
        return busy / (total * elapsed) if elapsed > 0 else 0.0

    def _integrate_busy(self, now: float, in_use: int) -> None:
        with self._lock:
            if self._t0 is None:
                self._t0 = now
            elif self._last_tick is not None:
                # integrate the PREVIOUS interval at the slot count that
                # was actually held over it (`self._last_in_use`, sampled
                # at the end of the last pass) — using the fresh `in_use`
                # here would attribute this tick's resizes/releases
                # retroactively over the interval before they happened
                self._busy_slot_seconds += (
                    self._last_in_use * (now - self._last_tick))
            self._last_tick = now
            self._last_in_use = int(in_use)

    # -- one scheduling pass --------------------------------------------------
    def step(self, now: Optional[float] = None) -> Dict[str, Any]:
        now = time.monotonic() if now is None else now
        report = self.resources.report()
        self._integrate_busy(now, int(report["in_use"]))
        summary: Dict[str, Any] = {"reaped": [], "dispatched": [],
                                   "draining": [], "evicted": [],
                                   "resizing": [], "resized": []}
        self._reap(summary)
        self._apply_control_requests(now, summary)
        self._poll_resizes(now, summary)
        self._enforce_drain_grace(now)
        self._place(now, summary)
        if self.serving_scaler is not None:
            try:
                self.serving_scaler.tick()
            except Exception:  # noqa: BLE001 — scaling is advisory
                logging.exception("pod: serving scaler tick failed")
        report = self.resources.report()
        total = int(report["total"]) or 1
        _slot_util.set(int(report["in_use"]) / total)
        _queue_depth.set(len(self.queue.queued()))
        with self._lock:
            _jobs_running.set(len(self._handles))
            # what this pass allocated/released holds until the next
            # tick — that's the value the busy integral must carry
            self._last_in_use = int(report["in_use"])
        summary["free_slots"] = int(report["free"])
        return summary

    def _reap(self, summary: Dict[str, Any]) -> None:
        with self._lock:
            handles = dict(self._handles)
        for job_id, handle in handles.items():
            rc = handle.poll()
            if rc is None:
                continue
            self.resources.release(handle.ctx.run_id)
            job = self.queue.get(job_id)
            tenant = job["tenant"] if job else "default"
            draining = bool(job and job["state"] == JobState.PREEMPTING)
            with self._lock:
                resize = self._resizes.pop(job_id, None)
            if resize is not None:
                # died (or was killed) with a resize in flight: the
                # boundary checkpoint still exists, so the fallback
                # ladder degrades this to a clean preempt-resume — the
                # resize can never be worse than a preemption
                self._finish_resize(job or {"job_id": job_id,
                                            "tenant": tenant},
                                    resize, "fallback_preempt", None)
                draining = draining or rc != 0
            if job is None:
                pass
            elif job["cancel_requested"]:
                self.queue.mark_finished(job_id, JobState.CANCELLED, rc)
            elif rc == PREEMPTED_EXIT_CODE or (draining and rc != 0):
                # a drained job that died non-zero (grace kill, SIGTERM)
                # still resumes from its last boundary checkpoint — the
                # checkpoint is written on every accepted upload, so even
                # a hard kill loses no completed round
                self.queue.requeue_preempted(job_id, rc)
                _preempted_total.labels(tenant=tenant).inc()
                ledger.event("scheduler", "requeue", job_id=job_id,
                             tenant=tenant, rc=rc)
            elif rc == 0:
                self.queue.mark_finished(job_id, JobState.FINISHED, 0)
                ledger.event("scheduler", "finish", job_id=job_id,
                             tenant=tenant, rc=0)
            else:
                self.queue.mark_finished(job_id, JobState.FAILED, rc)
                ledger.event("scheduler", "finish", job_id=job_id,
                             tenant=tenant, rc=rc)
            with self._lock:
                self._handles.pop(job_id, None)
                self._drain_started.pop(job_id, None)
            try:
                os.remove(handle.ctx.drain_path)
            except OSError:
                pass
            clear_resize(getattr(handle.ctx, "resize_path", None)
                         or self._resize_path(handle.ctx.run_id))
            summary["reaped"].append((job_id, rc))

    def _apply_control_requests(self, now: float,
                                summary: Dict[str, Any]) -> None:
        for job in self.queue.active():
            job_id = job["job_id"]
            with self._lock:
                handle = self._handles.get(job_id)
            if handle is None:
                continue
            if job["cancel_requested"]:
                handle.kill()
            elif (job["state"] == JobState.RUNNING
                  and job["preempt_requested"]):
                self._drain(job, handle, now, summary)
            elif (job["state"] == JobState.RUNNING
                  and job["resize_requested"]):
                with self._lock:
                    started = job["job_id"] in self._resizes
                if not started:
                    self._start_resize(job, int(job["resize_requested"]),
                                       now, summary)

    def _drain(self, job: Dict[str, Any], handle: Any, now: float,
               summary: Dict[str, Any]) -> None:
        handle.drain()
        self.queue.mark_preempting(job["job_id"])
        ledger.event("scheduler", "preempt", job_id=job["job_id"],
                     tenant=str(job["tenant"]))
        with self._lock:
            self._drain_started.setdefault(job["job_id"], now)
        summary["draining"].append(job["job_id"])

    # -- elastic resize -------------------------------------------------------
    def _resize_path(self, run_id: str) -> str:
        return os.path.join(self.queue.root, "resize", f"{run_id}.resize")

    def _start_resize(self, job: Dict[str, Any], target: int, now: float,
                      summary: Dict[str, Any]) -> None:
        """Announce a round-boundary resize to a RUNNING elastic job.
        A grow pre-allocates the extra slots under the job's run_id
        FIRST (no announce if the pod can't deliver them — the flag
        stays set and retries when slots free up); a shrink keeps every
        slot pinned until the workload acks the re-mesh."""
        job_id, run_id = job["job_id"], job["run_id"]
        with self._lock:
            if job_id in self._resizes:
                return  # one resize in flight at a time
        cur = int(job["n_slots"])
        target = self.queue.clamp_elastic(job, target)
        if target == cur or not run_id:
            self.queue.record_resize(job_id, cur, cur, "noop", 0.0,
                                     slots=job["slots"])
            return
        extra: List[int] = []
        if target > cur:
            extra = self.resources.allocate_extra(run_id, target - cur)
            if not extra:
                return  # not enough free slots yet — retry next tick
            slots_after = list(job["slots"]) + extra
        else:
            slots_after = list(job["slots"])[:target]
        path = self._resize_path(run_id)
        signal_resize(path, target, cur)
        with self._lock:
            self._resizes[job_id] = {
                "t0": now, "from": cur, "to": target, "run_id": run_id,
                "path": path, "slots_after": slots_after, "extra": extra}
        ledger.event("scheduler", "resize_start", job_id=job_id,
                     tenant=str(job["tenant"]),
                     **{"from": cur, "to": target})
        summary["resizing"].append(job_id)

    def _poll_resizes(self, now: float, summary: Dict[str, Any]) -> None:
        with self._lock:
            resizes = dict(self._resizes)
        for job_id, st in resizes.items():
            job = self.queue.get(job_id)
            if job is None or job["state"] != JobState.RUNNING:
                continue  # death/cancel paths settle it in _reap
            ack = read_resize_ack(st["path"])
            if ack is not None and ack.get("outcome") == "ok":
                if st["to"] < st["from"]:
                    freed = [s for s in job["slots"]
                             if s not in st["slots_after"]]
                    self.resources.release_slots(st["run_id"], freed)
                with self._lock:
                    self._resizes.pop(job_id, None)
                self._finish_resize(job, st, "ok",
                                    ack.get("downtime_s"))
                summary["resized"].append((job_id, st["to"]))
            elif ack is not None:
                self._resize_fallback(job, st, now, summary)
            elif now - st["t0"] > self.resize_grace_s:
                logging.warning(
                    "pod: job %s resize %d->%d exceeded grace (%.0fs) — "
                    "falling back to preempt", job_id, st["from"],
                    st["to"], self.resize_grace_s)
                self._resize_fallback(job, st, now, summary)

    def _resize_fallback(self, job: Dict[str, Any], st: Dict[str, Any],
                         now: float, summary: Dict[str, Any]) -> None:
        """The ladder's middle rung: the in-place re-mesh didn't land, so
        degrade to the PR-11 preempt path — drain at the next boundary,
        requeue with resume.  Pre-allocated grow slots go back first."""
        with self._lock:
            self._resizes.pop(job["job_id"], None)
        if st["extra"]:
            self.resources.release_slots(st["run_id"], st["extra"])
        self._finish_resize(job, st, "fallback_preempt", None)
        with self._lock:
            handle = self._handles.get(job["job_id"])
        if handle is not None:
            self._drain(job, handle, now, summary)

    def _finish_resize(self, job: Dict[str, Any], st: Dict[str, Any],
                       outcome: str,
                       downtime_s: Optional[float]) -> None:
        clear_resize(st["path"])
        self.queue.record_resize(
            job["job_id"], st["from"], st["to"], outcome,
            downtime_s, slots=st["slots_after"] if outcome == "ok"
            else None)
        direction = "grow" if st["to"] > st["from"] else "shrink"
        _resizes_total.labels(
            direction=direction,
            outcome="ok" if outcome == "ok" else "fallback").inc()
        if downtime_s is not None:
            _resize_downtime.observe(float(downtime_s))
        ledger.event("scheduler", "resize", job_id=job["job_id"],
                     tenant=str(job.get("tenant", "default")),
                     outcome=outcome, downtime_s=downtime_s,
                     **{"from": st["from"], "to": st["to"]})

    def _enforce_drain_grace(self, now: float) -> None:
        with self._lock:
            drains = dict(self._drain_started)
        for job_id, t0 in drains.items():
            if now - t0 <= self.drain_grace_s:
                continue
            with self._lock:
                handle = self._handles.get(job_id)
            if handle is not None:
                logging.warning(
                    "pod: job %s exceeded drain grace (%.0fs) — killing",
                    job_id, self.drain_grace_s)
                handle.kill()

    def _place(self, now: float, summary: Dict[str, Any]) -> None:
        queued = self.queue.queued()
        running = self.queue.active()
        queued_ids = {j["job_id"] for j in queued}
        with self._lock:
            # reservations for jobs that left the queue (dispatched,
            # cancelled) are dead — drop them before planning
            stale = [jid for jid in self._reservations
                     if jid not in queued_ids]
            for jid in stale:
                self._reservations.pop(jid, None)
            reserved = dict(self._reservations)
        free = len(self.resources.available_slots())
        plan = self.allocator.plan(queued, running, free, reserved)
        for victim in plan.evict:
            with self._lock:
                handle = self._handles.get(victim["job_id"])
            if handle is not None:
                self._drain(victim, handle, now, summary)
                _evictions_total.labels(tenant=victim["tenant"]).inc()
                summary["evicted"].append(victim["job_id"])
        # elastic decisions: land the flag on the queue row (the same
        # path `fedml jobs resize` takes) and announce immediately —
        # the pledge in plan.reserve holds the freed slots for the
        # blocked job across the ticks the re-mesh needs
        for victim, new in plan.shrink:
            target = self.queue.request_resize(victim["job_id"], new)
            if target is not None:
                self._start_resize(self.queue.get(victim["job_id"]),
                                   target, now, summary)
        for job, new in plan.grow:
            target = self.queue.request_resize(job["job_id"], new)
            if target is not None:
                self._start_resize(self.queue.get(job["job_id"]),
                                   target, now, summary)
        with self._lock:
            self._reservations.update(plan.reserve)
        for job in plan.dispatch:
            if self._dispatch(job):
                summary["dispatched"].append(job["job_id"])

    def _dispatch(self, job: Dict[str, Any]) -> bool:
        run_id = uuid.uuid4().hex[:12]
        slots = self.resources.allocate(run_id, int(job["n_slots"]))
        if not slots:
            return False  # lost a race against another dispatcher
        job_id = job["job_id"]
        drain_path = os.path.join(self.queue.root, "drain",
                                  f"{run_id}.drain")
        resize_path = self._resize_path(run_id)
        log_dir = os.path.join(self.queue.root, "logs", job_id, run_id)
        env = {
            "FEDML_TPU_DRAIN_FILE": drain_path,
            "FEDML_TPU_RESIZE_FILE": resize_path,
            "FEDML_TPU_LOG_DIR": log_dir,
            "FEDML_TPU_AOT_CACHE_DIR": self.aot_cache_dir,
            "FEDML_CURRENT_RUN_ID": run_id,
            "FEDML_TPU_JOB_ID": job_id,
            "FEDML_TPU_JOB_TENANT": str(job["tenant"]),
            "FEDML_TPU_SLOTS": ",".join(str(s) for s in slots),
        }
        env.update(job["env"])
        ctx = JobContext(job_id, run_id, slots, env,
                         resume=bool(job["resume"]),
                         drain_path=drain_path, log_dir=log_dir,
                         resize_path=resize_path)
        command = str(job["command"]).replace(
            "{resume}",
            "--resume-from latest" if job["resume"] else "").strip()
        try:
            handle = self.runner.start(job, ctx, command)
        except Exception:  # noqa: BLE001 — a bad job spec must not take
            # the scheduler down with it
            logging.exception("pod: dispatch of %s failed", job_id)
            self.resources.release(run_id)
            self.queue.mark_finished(job_id, JobState.FAILED, None)
            return False
        pid = getattr(getattr(handle, "proc", None), "pid", None)
        self.resources.set_pid(run_id, pid if pid else os.getpid())
        self.queue.mark_dispatched(job_id, run_id, slots, log_dir)
        wait_s = max(0.0, time.time() - float(job["submitted_ts"] or 0.0))
        _queue_wait.labels(tenant=str(job["tenant"])).observe(wait_s)
        with self._lock:
            self._handles[job_id] = handle
            self._reservations.pop(job_id, None)
        ledger.event("scheduler", "dispatch", job_id=job_id,
                     tenant=str(job["tenant"]), run=run_id,
                     slots=len(slots), resume=bool(job["resume"]))
        logging.info("pod: dispatched %s (%s/%s, %d slots, run %s%s)",
                     job["name"], job["tenant"], job["kind"], len(slots),
                     run_id, ", resume" if job["resume"] else "")
        return True

    # -- conveniences ---------------------------------------------------------
    def run_until_idle(self, timeout_s: float = 300.0,
                       poll_s: float = 0.05) -> bool:
        """Synchronously step until the queue drains (no QUEUED and no
        active jobs).  Returns False on timeout.  Test/driver helper —
        the daemon uses `start()` instead."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            self.step()
            stats = self.queue.stats()
            if not any(stats.get(s, 0) for s in JobState.ACTIVE):
                return True
            time.sleep(poll_s)
        return False
