"""Multi-tenant pod scheduler — many federated jobs, one TPU pod.

Capability parity+: the reference FedML's largest plane is its MLOps
scheduler (~25.4k LoC of launch/run/deploy runners); this package is the
TPU-era equivalent scoped to ONE device pool — a control plane that
gang-schedules mesh slices from a shared `ComputeResourceDB` to mixed
workloads (Parrot sims, cross-silo rounds, serving replicas):

* `JobSpec` / `JobQueue` — YAML job submissions in a shared sqlite queue
  (`fedml jobs submit|list|status|preempt|cancel`);
* `GangAllocator` — dispatch only when the FULL gang fits, weighted
  fair-share across tenants plus priority eviction of preemptible jobs;
* `PodScheduler` — the dispatch loop: round-boundary preemption (drain
  signal → the server force-saves its `RoundCheckpointer` state at the
  next boundary → exits `PREEMPTED_EXIT_CODE` → requeued with
  `--resume-from latest`), per-tenant AOT-cache sharing
  (`FEDML_TPU_AOT_CACHE_DIR`), per-job mlops isolation
  (`FEDML_TPU_LOG_DIR`), and the queue metrics plane;
* `ServingReplicaScaler` — serving-replica jobs scale their slot demand
  from the PR-9 decode histograms via `scheduler.autoscaler`.

See docs/SCHEDULER.md for the job YAML schema and lifecycle.
"""

from .jobspec import (  # noqa: F401
    JOB_KINDS,
    KIND_CROSS_SILO,
    KIND_PARROT,
    KIND_SERVING,
    PREEMPTED_EXIT_CODE,
    JobSpec,
    JobState,
)
from .queue import JobQueue, pod_root  # noqa: F401
from .allocator import GangAllocator, PlacementPlan  # noqa: F401
from .runners import (  # noqa: F401
    CallableJobRunner,
    JobContext,
    SubprocessJobRunner,
)
from .scheduler import PodScheduler  # noqa: F401
from .serving_scaler import ServingReplicaScaler  # noqa: F401
