"""Job specifications for the pod scheduler.

A job is ONE gang-scheduled unit of work: it runs only when its full slot
demand (a mesh slice of the pod) can be allocated at once.  The YAML
schema extends the launcher's job.yaml contract (`local_launcher.JobConfig`)
with the multi-tenant fields:

```yaml
job_name: team-a-sim          # display name
tenant: team-a                # fair-share accounting bucket
kind: parrot                  # parrot | cross_silo | serving
priority: 10                  # higher evicts lower (preemptible) jobs
slots: 4                      # gang size — device slots held while running
command: fedml run --cf fedml_config.yaml {resume}
workdir: .                    # resolved relative to the YAML file
preemptible: true             # may be drained for higher-priority work
elastic:                      # optional: round-boundary resizable gang
  min_slots: 2                # never shrunk below this
  max_slots: 8                # never grown past this
fedml_env:                    # extra environment for the dispatch
  FEDML_TPU_FLIGHT_RECORDER: "1"
```

`{resume}` in the command expands to ``--resume-from latest`` when the job
is re-dispatched after a round-boundary preemption, and to the empty
string on the first dispatch — the job script stays a single line either
way.

An **elastic** job declares a slot range instead of a fixed gang: the
allocator may shrink it toward ``min_slots`` under pressure (instead of
evicting it) and grow it back toward ``max_slots`` when slots free up,
both at round boundaries via the resize file (docs/SCHEDULER.md
"Elastic resize").  A job without an ``elastic`` block keeps the fixed
gang contract: it is never resized, only preempted whole.
"""

from __future__ import annotations

import dataclasses
import os
import uuid
from typing import Any, Dict, Optional

#: exit code a dispatched job uses to report "preempted at a round
#: boundary, checkpoint saved — requeue me with --resume-from latest".
#: BSD's EX_TEMPFAIL: a transient condition, retry later.
PREEMPTED_EXIT_CODE = 75

KIND_PARROT = "parrot"
KIND_CROSS_SILO = "cross_silo"
KIND_SERVING = "serving"
JOB_KINDS = (KIND_PARROT, KIND_CROSS_SILO, KIND_SERVING)


class JobState:
    """Lifecycle: QUEUED → RUNNING → {FINISHED, FAILED} | PREEMPTING →
    (exit) → QUEUED again with ``resume=1`` (or PREEMPTED when the job is
    not requeued, e.g. cancelled mid-drain).  CANCELLED is terminal from
    any non-terminal state."""

    QUEUED = "QUEUED"
    RUNNING = "RUNNING"
    PREEMPTING = "PREEMPTING"
    PREEMPTED = "PREEMPTED"
    FINISHED = "FINISHED"
    FAILED = "FAILED"
    CANCELLED = "CANCELLED"

    ACTIVE = (QUEUED, RUNNING, PREEMPTING)
    TERMINAL = (PREEMPTED, FINISHED, FAILED, CANCELLED)


@dataclasses.dataclass
class JobSpec:
    name: str
    kind: str = KIND_CROSS_SILO
    tenant: str = "default"
    priority: int = 0
    n_slots: int = 1
    command: str = ""
    workdir: str = "."
    env: Dict[str, str] = dataclasses.field(default_factory=dict)
    preemptible: bool = True
    #: elastic slot range — both 0 means "not elastic" (fixed gang)
    min_slots: int = 0
    max_slots: int = 0
    job_id: str = dataclasses.field(
        default_factory=lambda: uuid.uuid4().hex[:12])

    @property
    def elastic(self) -> bool:
        return int(self.min_slots) > 0 or int(self.max_slots) > 0

    def validate(self) -> "JobSpec":
        if self.kind not in JOB_KINDS:
            raise ValueError(
                f"job kind {self.kind!r} not in {JOB_KINDS}")
        if int(self.n_slots) < 1:
            raise ValueError(f"slots must be >= 1, got {self.n_slots}")
        if not self.name:
            raise ValueError("job_name is required")
        if self.elastic:
            lo, hi = int(self.min_slots), int(self.max_slots)
            if lo < 1:
                raise ValueError(
                    f"elastic.min_slots must be >= 1, got {lo}")
            if hi < lo:
                raise ValueError(
                    f"elastic.max_slots {hi} < min_slots {lo}")
            if not lo <= int(self.n_slots) <= hi:
                raise ValueError(
                    f"slots {self.n_slots} outside the elastic range "
                    f"[{lo}, {hi}]")
        return self

    @classmethod
    def from_dict(cls, raw: Dict[str, Any],
                  base_dir: Optional[str] = None) -> "JobSpec":
        workdir = str(raw.get("workdir", ".") or ".")
        if base_dir is not None:
            workdir = os.path.normpath(os.path.join(base_dir, workdir))
        slots = raw.get("slots", raw.get("n_slots"))
        elastic = raw.get("elastic") or {}
        if not isinstance(elastic, dict):
            raise ValueError(
                "elastic must be a mapping with min_slots/max_slots, "
                f"got {elastic!r}")
        n_slots = int(1 if slots is None else slots)
        min_slots = int(elastic.get("min_slots", 0) or 0)
        max_slots = int(elastic.get("max_slots", 0) or 0)
        if elastic:
            # a bare `elastic: {}` (or a one-sided range) defaults the
            # missing bound to the declared gang size
            min_slots = min_slots or n_slots
            max_slots = max_slots or n_slots
        return cls(
            name=str(raw.get("job_name", "")
                     or f"job_{uuid.uuid4().hex[:8]}"),
            kind=str(raw.get("kind", KIND_CROSS_SILO)),
            tenant=str(raw.get("tenant", "default") or "default"),
            priority=int(raw.get("priority", 0) or 0),
            n_slots=n_slots,
            command=str(raw.get("command", raw.get("job", "")) or ""),
            workdir=workdir,
            env={k: str(v) for k, v in
                 dict(raw.get("fedml_env", raw.get("env", {})) or {}
                      ).items()},
            preemptible=bool(raw.get("preemptible", True)),
            min_slots=min_slots,
            max_slots=max_slots,
        ).validate()

    @classmethod
    def from_yaml(cls, path: str) -> "JobSpec":
        import yaml

        with open(path) as f:
            raw = yaml.safe_load(f) or {}
        return cls.from_dict(raw,
                             base_dir=os.path.dirname(os.path.abspath(path)))

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)

    def render_command(self, resume: bool) -> str:
        """Expand the ``{resume}`` placeholder for this dispatch."""
        return self.command.replace(
            "{resume}", "--resume-from latest" if resume else "").strip()
