"""Job specifications for the pod scheduler.

A job is ONE gang-scheduled unit of work: it runs only when its full slot
demand (a mesh slice of the pod) can be allocated at once.  The YAML
schema extends the launcher's job.yaml contract (`local_launcher.JobConfig`)
with the multi-tenant fields:

```yaml
job_name: team-a-sim          # display name
tenant: team-a                # fair-share accounting bucket
kind: parrot                  # parrot | cross_silo | serving
priority: 10                  # higher evicts lower (preemptible) jobs
slots: 4                      # gang size — device slots held while running
command: fedml run --cf fedml_config.yaml {resume}
workdir: .                    # resolved relative to the YAML file
preemptible: true             # may be drained for higher-priority work
fedml_env:                    # extra environment for the dispatch
  FEDML_TPU_FLIGHT_RECORDER: "1"
```

`{resume}` in the command expands to ``--resume-from latest`` when the job
is re-dispatched after a round-boundary preemption, and to the empty
string on the first dispatch — the job script stays a single line either
way.
"""

from __future__ import annotations

import dataclasses
import os
import uuid
from typing import Any, Dict, Optional

#: exit code a dispatched job uses to report "preempted at a round
#: boundary, checkpoint saved — requeue me with --resume-from latest".
#: BSD's EX_TEMPFAIL: a transient condition, retry later.
PREEMPTED_EXIT_CODE = 75

KIND_PARROT = "parrot"
KIND_CROSS_SILO = "cross_silo"
KIND_SERVING = "serving"
JOB_KINDS = (KIND_PARROT, KIND_CROSS_SILO, KIND_SERVING)


class JobState:
    """Lifecycle: QUEUED → RUNNING → {FINISHED, FAILED} | PREEMPTING →
    (exit) → QUEUED again with ``resume=1`` (or PREEMPTED when the job is
    not requeued, e.g. cancelled mid-drain).  CANCELLED is terminal from
    any non-terminal state."""

    QUEUED = "QUEUED"
    RUNNING = "RUNNING"
    PREEMPTING = "PREEMPTING"
    PREEMPTED = "PREEMPTED"
    FINISHED = "FINISHED"
    FAILED = "FAILED"
    CANCELLED = "CANCELLED"

    ACTIVE = (QUEUED, RUNNING, PREEMPTING)
    TERMINAL = (PREEMPTED, FINISHED, FAILED, CANCELLED)


@dataclasses.dataclass
class JobSpec:
    name: str
    kind: str = KIND_CROSS_SILO
    tenant: str = "default"
    priority: int = 0
    n_slots: int = 1
    command: str = ""
    workdir: str = "."
    env: Dict[str, str] = dataclasses.field(default_factory=dict)
    preemptible: bool = True
    job_id: str = dataclasses.field(
        default_factory=lambda: uuid.uuid4().hex[:12])

    def validate(self) -> "JobSpec":
        if self.kind not in JOB_KINDS:
            raise ValueError(
                f"job kind {self.kind!r} not in {JOB_KINDS}")
        if int(self.n_slots) < 1:
            raise ValueError(f"slots must be >= 1, got {self.n_slots}")
        if not self.name:
            raise ValueError("job_name is required")
        return self

    @classmethod
    def from_dict(cls, raw: Dict[str, Any],
                  base_dir: Optional[str] = None) -> "JobSpec":
        workdir = str(raw.get("workdir", ".") or ".")
        if base_dir is not None:
            workdir = os.path.normpath(os.path.join(base_dir, workdir))
        slots = raw.get("slots", raw.get("n_slots"))
        return cls(
            name=str(raw.get("job_name", "")
                     or f"job_{uuid.uuid4().hex[:8]}"),
            kind=str(raw.get("kind", KIND_CROSS_SILO)),
            tenant=str(raw.get("tenant", "default") or "default"),
            priority=int(raw.get("priority", 0) or 0),
            n_slots=int(1 if slots is None else slots),
            command=str(raw.get("command", raw.get("job", "")) or ""),
            workdir=workdir,
            env={k: str(v) for k, v in
                 dict(raw.get("fedml_env", raw.get("env", {})) or {}
                      ).items()},
            preemptible=bool(raw.get("preemptible", True)),
        ).validate()

    @classmethod
    def from_yaml(cls, path: str) -> "JobSpec":
        import yaml

        with open(path) as f:
            raw = yaml.safe_load(f) or {}
        return cls.from_dict(raw,
                             base_dir=os.path.dirname(os.path.abspath(path)))

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)

    def render_command(self, resume: bool) -> str:
        """Expand the ``{resume}`` placeholder for this dispatch."""
        return self.command.replace(
            "{resume}", "--resume-from latest" if resume else "").strip()
