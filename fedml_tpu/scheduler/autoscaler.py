"""Endpoint replica autoscaler.

Capability parity+: reference `comm_utils/job_monitor.py` watches endpoint
replicas and releases/restarts them (SURVEY §2.12 "autoscale/reset logic");
this module adds the explicit scaling POLICY the reference leaves implicit —
a latency/queue-depth target controller suitable for the serving engines:

* observe(qps, latency_s, queue_depth) windows per tick;
* desired = clamp by target latency AND target per-replica qps;
* hysteresis: scale up fast (any breach, never blocked by cooldown),
  scale down slowly (sustained under-utilization, and only after
  ``cooldown_s`` since the last scale event);
* pure decision logic — applying the decision is a callback, so it drives
  local engines, container replicas, or k8s alike.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable, List, Optional


@dataclasses.dataclass
class AutoscalePolicy:
    min_replicas: int = 1
    max_replicas: int = 8
    target_latency_s: float = 1.0      # scale up when p50 exceeds this
    target_qps_per_replica: float = 10.0
    scale_down_idle_ticks: int = 3     # sustained low load before shrinking
    cooldown_s: float = 30.0


class ReplicaAutoscaler:
    def __init__(self, policy: Optional[AutoscalePolicy] = None,
                 apply_fn: Optional[Callable[[int], None]] = None,
                 clock: Callable[[], float] = time.monotonic) -> None:
        self.policy = policy or AutoscalePolicy()
        self.apply_fn = apply_fn
        self.clock = clock
        self.replicas = self.policy.min_replicas
        self._idle_ticks = 0
        self._last_scale_t: float = -1e18
        self.history: List[int] = []

    # -- decision ------------------------------------------------------------
    def observe(self, qps: float, latency_s: float,
                queue_depth: int = 0) -> int:
        """Feed one metrics window; returns the (possibly new) replica
        count.  Calls ``apply_fn`` only when the count changes."""
        p = self.policy
        want = self.replicas
        overloaded = (latency_s > p.target_latency_s
                      or qps > p.target_qps_per_replica * self.replicas
                      or queue_depth > 2 * self.replicas)
        underloaded = (latency_s < 0.5 * p.target_latency_s
                       and qps < 0.5 * p.target_qps_per_replica
                       * max(self.replicas - 1, 1)
                       and queue_depth == 0)
        if overloaded:
            self._idle_ticks = 0
            # jump straight to the load-implied size (fast scale-up)
            by_qps = -(-qps // max(p.target_qps_per_replica, 1e-9))
            want = max(self.replicas + 1, int(by_qps))
        elif underloaded:
            self._idle_ticks += 1
            if self._idle_ticks >= p.scale_down_idle_ticks:
                want = self.replicas - 1       # shrink one step at a time
                self._idle_ticks = 0
        else:
            self._idle_ticks = 0
        want = max(p.min_replicas, min(p.max_replicas, want))

        now = self.clock()
        # scale-up is exempt from the cooldown ("scale up fast: any
        # breach"); only scale-DOWN waits out cooldown_s since the last
        # scale event, so a latency breach right after a resize still grows
        # the fleet immediately
        in_cooldown = (now - self._last_scale_t) < p.cooldown_s
        if want != self.replicas and not (want < self.replicas and in_cooldown):
            self.replicas = want
            self._last_scale_t = now
            self.history.append(want)
            if self.apply_fn:
                self.apply_fn(want)
        return self.replicas
