"""Job monitor daemon.

Capability parity: reference `comm_utils/job_monitor.py:37-699`: a periodic
watcher over run processes and serving endpoints — detect dead processes
still marked RUNNING, flip their status, and invoke recovery hooks
(endpoint replica reset / autoscale in the reference; pluggable callbacks
here).
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from ..core.mlops import metrics, tracing
from . import local_launcher

_checks_total = metrics.counter(
    "fedml_jobmon_checks_total", "Job-monitor reconciliation passes")
_dead_runs_total = metrics.counter(
    "fedml_jobmon_dead_runs_total",
    "RUNNING runs whose process was found dead and flipped to FAILED")
_endpoint_unhealthy_total = metrics.counter(
    "fedml_jobmon_endpoint_unhealthy_total",
    "Endpoint health-probe failures", labels=("endpoint",))


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except PermissionError:
        return True
    return True


class JobMonitor:
    """Periodically reconcile the runs db with process reality."""

    def __init__(self, interval_s: float = 5.0,
                 on_dead_run: Optional[Callable[[Dict[str, Any]], None]] = None
                 ) -> None:
        self.interval_s = interval_s
        self.on_dead_run = on_dead_run
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.endpoint_probes: Dict[str, Callable[[], bool]] = {}
        self.endpoint_resets: Dict[str, Callable[[], None]] = {}

    def start(self) -> "JobMonitor":
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="job-monitor")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=self.interval_s + 1)

    def register_endpoint(self, name: str, probe: Callable[[], bool],
                          reset: Optional[Callable[[], None]] = None) -> None:
        """Watch a serving endpoint (reference endpoint replica monitor):
        `probe()` returns health; on failure `reset()` is invoked."""
        # single GIL-atomic dict store; the monitor thread only iterates a
        # list() snapshot, so registration can never corrupt its pass
        self.endpoint_probes[name] = probe  # fedml: noqa[CONC001]
        if reset:
            self.endpoint_resets[name] = reset  # fedml: noqa[CONC001]

    def check_once(self) -> List[Dict[str, Any]]:
        """One reconciliation pass; returns runs flipped to FAILED."""
        _checks_total.inc()
        flipped = []
        with tracing.span("jobmon.check"):
            return self._check_once_inner(flipped)

    def _check_once_inner(self, flipped: List[Dict[str, Any]]
                          ) -> List[Dict[str, Any]]:
        for run in local_launcher.list_runs(limit=200):
            if run["status"] != "RUNNING":
                continue
            full = local_launcher.get_run(run["run_id"]) or {}
            pid = full.get("pid")
            if pid and not _pid_alive(int(pid)):
                local_launcher.update_run_status(
                    run["run_id"], "FAILED", returncode=-1)
                logging.warning("job monitor: run %s (pid %s) died; "
                                "marked FAILED", run["run_id"], pid)
                _dead_runs_total.inc()
                flipped.append(full)
                if self.on_dead_run:
                    try:
                        self.on_dead_run(full)
                    except Exception:  # noqa: BLE001
                        logging.exception("on_dead_run hook failed")
        for name, probe in list(self.endpoint_probes.items()):
            try:
                healthy = probe()
            except Exception:  # noqa: BLE001
                healthy = False
            if not healthy:
                logging.warning("job monitor: endpoint %s unhealthy", name)
                _endpoint_unhealthy_total.labels(endpoint=name).inc()
                reset = self.endpoint_resets.get(name)
                if reset:
                    try:
                        reset()
                    except Exception:  # noqa: BLE001
                        logging.exception("endpoint reset failed: %s", name)
        return flipped

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.check_once()
            except Exception:  # noqa: BLE001
                logging.exception("job monitor pass failed")
