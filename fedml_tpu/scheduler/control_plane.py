"""HTTP control plane for the fleet (VERDICT r3 item 7).

Capability parity: the reference's launch path is CLI → REST backend →
MQTT to matched edges (`computing/scheduler/scheduler_entry/
launch_manager.py:25-645`, `run_manager.py` — FedMLRunStarted/
RunStartedModel over HTTP, then the agents pick the run up from the
broker).  This module is that REST tier, stdlib-only:

* ``ControlPlaneServer`` — ThreadingHTTPServer over a ``MasterAgent``:
  create/stop/status/wait runs, fleet listing, resource matching.
  Optional API key (``X-Api-Key`` header), the reference's account-key
  gate.
* ``ControlPlaneClient`` — urllib client; builds the job package
  LOCALLY (`fedml_tpu build` semantics) and uploads it base64 in the
  create-run request, exactly like the reference CLI uploads the
  package to S3 before dispatch.
* ``python -m fedml_tpu.scheduler.control_plane`` — standalone server
  entry point (the `fedml launch --remote http://...` target).

Endpoints (JSON in/out):
  GET  /healthz
  GET  /api/v1/fleet
  POST /api/v1/match          {num_edges, min_free_slots?, device_kind?}
  POST /api/v1/runs           {package_b64, edges?|match?,
                               config_overrides?, env?}
  GET  /api/v1/runs/<id>
  GET  /api/v1/runs/<id>/wait?timeout=<s>
  POST /api/v1/runs/<id>/stop

Pod job-queue tier (present when constructed with ``pod_queue=``; a
pod-only plane may pass ``master=None``):
  GET  /api/v1/pod/stats
  GET  /api/v1/pod/jobs?state=&tenant=&limit=
  GET  /api/v1/pod/jobs/<id>
  POST /api/v1/pod/jobs       {job_name, kind, tenant, slots, command, ...}
  POST /api/v1/pod/jobs/<id>/preempt
  POST /api/v1/pod/jobs/<id>/cancel
  POST /api/v1/pod/jobs/<id>/resize  {slots}
"""

from __future__ import annotations

import base64
import json
import re
import threading
import urllib.error
import urllib.request
from typing import Any, Dict, List, Optional

from ..core.mlops import metrics as _metrics
from ..utils.http_json import DeepBacklogHTTPServer, BadRequest, JsonHandler
from .agents import MasterAgent

_RUN_PATH = re.compile(r"^/api/v1/runs/([0-9a-f]+)(/(wait|stop))?$")
_POD_JOB_PATH = re.compile(
    r"^/api/v1/pod/jobs/([0-9a-f]+)(/(preempt|cancel|resize))?$")


class ControlPlaneServer:
    def __init__(self, master: Optional[MasterAgent],
                 host: str = "127.0.0.1", port: int = 0,
                 api_key: Optional[str] = None,
                 pod_queue: Optional[Any] = None) -> None:
        """``master`` drives the fleet/runs endpoints; ``pod_queue`` (a
        `pod.JobQueue`) enables the /api/v1/pod tier.  Either may be None
        — a pod-only control plane passes ``master=None``; the missing
        tier answers 503."""
        self.master = master
        self.pod_queue = pod_queue
        self.api_key = api_key or None
        plane = self

        class Handler(JsonHandler):
            _reply = JsonHandler.reply

            def _authed(self) -> bool:
                if plane.api_key is None:
                    return True
                return self.headers.get("X-Api-Key") == plane.api_key

            def do_GET(self) -> None:  # noqa: N802
                if self.path == "/healthz":
                    return self._reply(200, {"ok": True})
                if self.path == "/metrics":
                    # Prometheus text exposition of the process-wide typed
                    # registry (round/trainer/serving/jobmon series) —
                    # unauthenticated like /healthz, it's a scrape target
                    body = _metrics.render_prometheus().encode()
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     "text/plain; version=0.0.4; "
                                     "charset=utf-8")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return None
                if not self._authed():
                    return self._reply(401, {"error": "bad api key"})
                path = self.path.split("?")[0]
                if path.startswith("/api/v1/pod"):
                    return self._pod_get(path)
                if plane.master is None:
                    return self._reply(503, {"error": "no master agent"})
                if self.path == "/api/v1/fleet":
                    return self._reply(200, {"edges": plane.master.fleet()})
                m = _RUN_PATH.match(self.path.split("?")[0])
                if m and not m.group(3):
                    try:
                        return self._reply(200, {
                            "run_id": m.group(1),
                            "edges": plane.master.status(m.group(1))})
                    except KeyError:
                        return self._reply(404, {"error": "unknown run"})
                if m and m.group(3) == "wait":
                    try:
                        timeout = self.query_float("timeout", 300.0)
                        return self._reply(200, plane.master.wait(
                            m.group(1), timeout=timeout))
                    except BadRequest as e:
                        return self._reply(400, {"error": str(e)})
                    except KeyError:
                        return self._reply(404, {"error": "unknown run"})
                return self._reply(404, {"error": "not found"})

            def _pod_get(self, path: str):
                if plane.pod_queue is None:
                    return self._reply(503, {"error": "no pod queue"})
                if path == "/api/v1/pod/stats":
                    return self._reply(200, plane.pod_queue.stats())
                if path == "/api/v1/pod/jobs":
                    from urllib.parse import parse_qs, urlparse

                    q = parse_qs(urlparse(self.path).query)
                    rows = plane.pod_queue.list_jobs(
                        state=(q.get("state") or [None])[0],
                        tenant=(q.get("tenant") or [None])[0],
                        limit=int((q.get("limit") or ["200"])[0]))
                    return self._reply(200, {"jobs": rows})
                m = _POD_JOB_PATH.match(path)
                if m and not m.group(3):
                    row = plane.pod_queue.get(m.group(1))
                    if row is None:
                        return self._reply(404, {"error": "unknown job"})
                    return self._reply(200, row)
                return self._reply(404, {"error": "not found"})

            def _pod_post(self, body):
                if plane.pod_queue is None:
                    return self._reply(503, {"error": "no pod queue"})
                if self.path == "/api/v1/pod/jobs":
                    from .pod import JobSpec

                    try:
                        spec = JobSpec.from_dict(body)
                    except (ValueError, TypeError) as e:
                        return self._reply(400, {"error": str(e)})
                    plane.pod_queue.submit(spec)
                    return self._reply(200, {"job_id": spec.job_id})
                m = _POD_JOB_PATH.match(self.path)
                if m and m.group(3) == "preempt":
                    ok = plane.pod_queue.request_preempt(m.group(1))
                    return self._reply(200 if ok else 409,
                                       {"job_id": m.group(1),
                                        "preempt_requested": ok})
                if m and m.group(3) == "cancel":
                    ok = plane.pod_queue.request_cancel(m.group(1))
                    return self._reply(200 if ok else 409,
                                       {"job_id": m.group(1),
                                        "cancel_requested": ok})
                if m and m.group(3) == "resize":
                    try:
                        slots = int(body["slots"])
                    except (KeyError, TypeError, ValueError):
                        return self._reply(400,
                                           {"error": "slots required"})
                    target = plane.pod_queue.request_resize(
                        m.group(1), slots)
                    return self._reply(200 if target is not None else 409,
                                       {"job_id": m.group(1),
                                        "resize_requested":
                                            target is not None,
                                        "target_slots": target})
                return self._reply(404, {"error": "not found"})

            def do_POST(self) -> None:  # noqa: N802
                if not self._authed():
                    return self._reply(401, {"error": "bad api key"})
                try:
                    body = self.json_body()
                except BadRequest:
                    return self._reply(400, {"error": "bad json"})
                if self.path.startswith("/api/v1/pod"):
                    return self._pod_post(body)
                if plane.master is None:
                    return self._reply(503, {"error": "no master agent"})
                if self.path == "/api/v1/match":
                    try:
                        edges = plane.master.match_edges(
                            int(body.get("num_edges", 1)),
                            int(body.get("min_free_slots", 1)),
                            body.get("device_kind"),
                            float(body.get("max_age_s", 60.0)))
                        return self._reply(200, {"edges": edges})
                    except (ValueError, TypeError) as e:
                        return self._reply(400, {"error": str(e)})
                    except RuntimeError as e:
                        return self._reply(409, {"error": str(e)})
                if self.path == "/api/v1/runs":
                    if "package_b64" not in body:
                        return self._reply(400,
                                           {"error": "package_b64 required"})
                    try:
                        run_id = plane.master.create_run_from_package(
                            base64.b64decode(body["package_b64"]),
                            edges=body.get("edges"),
                            config_overrides=body.get("config_overrides"),
                            env=body.get("env"),
                            match=body.get("match"))
                        return self._reply(200, {"run_id": run_id})
                    except (ValueError, TypeError) as e:
                        return self._reply(400, {"error": str(e)})
                    except RuntimeError as e:
                        return self._reply(409, {"error": str(e)})
                m = _RUN_PATH.match(self.path)
                if m and m.group(3) == "stop":
                    try:
                        plane.master.stop_run(m.group(1))
                    except KeyError:
                        return self._reply(404, {"error": "unknown run"})
                    return self._reply(200, {"ok": True})
                return self._reply(404, {"error": "not found"})

        self._srv = DeepBacklogHTTPServer((host, port), Handler)
        self._srv.daemon_threads = True
        self.host, self.port = self._srv.server_address
        self._thread = threading.Thread(target=self._srv.serve_forever,
                                        daemon=True,
                                        name="fedml-control-plane")

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def start(self) -> "ControlPlaneServer":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._srv.shutdown()
        self._srv.server_close()


class ControlPlaneClient:
    """urllib client for the control plane (the `fedml launch --remote`
    transport)."""

    def __init__(self, base_url: str, api_key: Optional[str] = None,
                 timeout: float = 30.0) -> None:
        self.base = base_url.rstrip("/")
        self.api_key = api_key
        self.timeout = timeout

    def _call(self, method: str, path: str,
              body: Optional[Dict[str, Any]] = None,
              timeout: Optional[float] = None) -> Dict[str, Any]:
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(
            self.base + path, data=data, method=method,
            headers={"Content-Type": "application/json",
                     **({"X-Api-Key": self.api_key}
                        if self.api_key else {})})
        try:
            with urllib.request.urlopen(
                    req, timeout=timeout or self.timeout) as r:
                return json.loads(r.read().decode())
        except urllib.error.HTTPError as e:  # surface the server's error
            try:
                detail = json.loads(e.read().decode()).get("error", "")
            except Exception:  # noqa: BLE001
                detail = ""
            raise RuntimeError(
                f"control plane {e.code} on {path}: {detail}") from e

    def health(self) -> Dict[str, Any]:
        return self._call("GET", "/healthz")

    def metrics_text(self) -> str:
        """Raw Prometheus exposition from GET /metrics (not JSON)."""
        req = urllib.request.Request(self.base + "/metrics")
        with urllib.request.urlopen(req, timeout=self.timeout) as r:
            return r.read().decode()

    def fleet(self) -> Dict[str, Any]:
        return self._call("GET", "/api/v1/fleet")["edges"]

    def match(self, num_edges: int, **kw: Any) -> List[str]:
        return self._call("POST", "/api/v1/match",
                          {"num_edges": num_edges, **kw})["edges"]

    def create_run(self, job_yaml_path: str,
                   edges: Optional[List[str]] = None,
                   match: Optional[Dict[str, Any]] = None,
                   config_overrides: Optional[Dict[str, Any]] = None,
                   env: Optional[Dict[str, str]] = None) -> str:
        from .local_launcher import build_job_package

        zip_path = build_job_package(job_yaml_path)
        with open(zip_path, "rb") as f:
            pkg = base64.b64encode(f.read()).decode()
        return self._call("POST", "/api/v1/runs", {
            "package_b64": pkg, "edges": edges, "match": match,
            "config_overrides": config_overrides or {},
            "env": env or {}})["run_id"]

    def status(self, run_id: str) -> Dict[str, Any]:
        return self._call("GET", f"/api/v1/runs/{run_id}")["edges"]

    def wait(self, run_id: str, timeout: float = 300.0) -> Dict[str, Any]:
        return self._call(
            "GET", f"/api/v1/runs/{run_id}/wait?timeout={timeout}",
            timeout=timeout + 10.0)

    def stop_run(self, run_id: str) -> None:
        self._call("POST", f"/api/v1/runs/{run_id}/stop", {})

    # -- pod job queue -------------------------------------------------------
    def pod_submit(self, spec: Dict[str, Any]) -> str:
        """Submit a pod job from its YAML-shaped dict; returns job_id."""
        return self._call("POST", "/api/v1/pod/jobs", spec)["job_id"]

    def pod_jobs(self, state: Optional[str] = None,
                 tenant: Optional[str] = None) -> List[Dict[str, Any]]:
        qs = "&".join(f"{k}={v}" for k, v in
                      (("state", state), ("tenant", tenant)) if v)
        return self._call("GET", "/api/v1/pod/jobs"
                          + (f"?{qs}" if qs else ""))["jobs"]

    def pod_job(self, job_id: str) -> Dict[str, Any]:
        return self._call("GET", f"/api/v1/pod/jobs/{job_id}")

    def pod_preempt(self, job_id: str) -> bool:
        return self._call("POST", f"/api/v1/pod/jobs/{job_id}/preempt",
                          {})["preempt_requested"]

    def pod_cancel(self, job_id: str) -> bool:
        return self._call("POST", f"/api/v1/pod/jobs/{job_id}/cancel",
                          {})["cancel_requested"]

    def pod_resize(self, job_id: str, slots: int) -> Optional[int]:
        """Clamped target slot count, or None when the job can't resize
        (not found, finished, or RUNNING without an elastic range)."""
        try:
            return self._call(
                "POST", f"/api/v1/pod/jobs/{job_id}/resize",
                {"slots": int(slots)})["target_slots"]
        except RuntimeError as e:
            if "409" in str(e) or "404" in str(e):
                return None
            raise

    def pod_stats(self) -> Dict[str, int]:
        return self._call("GET", "/api/v1/pod/stats")


def main() -> None:
    import argparse
    import os
    import time

    p = argparse.ArgumentParser(description="fedml_tpu fleet control plane")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8899)
    p.add_argument("--channel", default="agents")
    p.add_argument("--store-dir", default=None)
    p.add_argument("--api-key", default=os.environ.get("FEDML_API_KEY"))
    p.add_argument("--pod-dir", default=None,
                   help="also expose the pod job queue at /api/v1/pod "
                        "(the `fedml jobs pod` daemon's state dir)")
    cli = p.parse_args()
    master = MasterAgent(channel=cli.channel, store_dir=cli.store_dir)
    pod_queue = None
    if cli.pod_dir is not None:
        from .pod import JobQueue

        pod_queue = JobQueue(cli.pod_dir)
    srv = ControlPlaneServer(master, cli.host, cli.port,
                             api_key=cli.api_key,
                             pod_queue=pod_queue).start()
    print(json.dumps({"control_plane": srv.url}), flush=True)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        srv.stop()


if __name__ == "__main__":
    main()
