"""Process-level model-serving replicas with autoscaling and self-healing.

Capability parity: reference `model_scheduler/device_model_deployment.py:
89-928` brings endpoints up as separate containers, the job monitor
(`comm_utils/job_monitor.py:63-699`) restarts dead replicas, and the
autoscale/reset logic resizes them.  TPU-era, container-free equivalent:
each replica is an OS PROCESS serving a model card over HTTP
(`replica_worker.py` → FedMLInferenceRunner); this manager

* spawns/retires replicas (``scale_to`` — the `ReplicaAutoscaler`'s
  apply_fn),
* health-checks and RESTARTS crashed replicas (monitor thread),
* round-robins requests across live replicas (the inference-gateway role
  of `device_model_inference.py`).
"""

from __future__ import annotations

import json
import logging
import os
import subprocess
import sys
import threading
import time
import urllib.request
from typing import Any, Dict, List, Optional

from ..core.mlops.lock_profiler import named_rlock


class _Replica:
    def __init__(self, proc: subprocess.Popen, port: int) -> None:
        self.proc = proc
        self.port = port
        self.restarts = 0


class ReplicaProcessManager:
    def __init__(self, card_name: str, registry_root: Optional[str] = None,
                 host: str = "127.0.0.1", base_port: int = 0,
                 ready_timeout_s: float = 60.0,
                 monitor_interval_s: float = 0.5) -> None:
        self.card_name = card_name
        self.registry_root = registry_root
        self.host = host
        # base_port 0 → pick a free ephemeral base once, then offset per slot
        self.base_port = base_port or self._free_port()
        self.ready_timeout_s = float(ready_timeout_s)
        self.monitor_interval_s = float(monitor_interval_s)
        self.replicas: List[Optional[_Replica]] = []
        self._rr = 0
        self._lock = named_rlock("ReplicaProcessManager._lock")       # replica-list access (fast)
        self._scale_lock = named_rlock("ReplicaProcessManager._scale_lock")  # lifecycle ops (slow)
        self._stop = threading.Event()
        self._monitor: Optional[threading.Thread] = None

    @staticmethod
    def _free_port() -> int:
        import socket

        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            return s.getsockname()[1]

    # -- lifecycle ----------------------------------------------------------
    def _spawn(self, slot: int) -> _Replica:
        port = self.base_port + slot
        cmd = [sys.executable, "-m",
               "fedml_tpu.scheduler.replica_worker",
               "--card", self.card_name, "--host", self.host,
               "--port", str(port)]
        if self.registry_root:
            cmd += ["--root", self.registry_root]
        env = dict(os.environ)
        env.setdefault("JAX_PLATFORMS", "cpu")  # replicas default off-chip
        proc = subprocess.Popen(cmd, env=env, stdout=subprocess.DEVNULL,
                                stderr=subprocess.STDOUT)
        rep = _Replica(proc, port)
        self._wait_ready(rep)
        logging.info("replica[%d] pid=%d serving on :%d", slot, proc.pid,
                     port)
        return rep

    def _wait_ready(self, rep: _Replica) -> None:
        deadline = time.time() + self.ready_timeout_s
        while time.time() < deadline and not self._stop.is_set():
            if rep.proc.poll() is not None:
                raise RuntimeError(
                    f"replica on :{rep.port} exited rc={rep.proc.returncode}"
                    " before becoming ready")
            try:
                with urllib.request.urlopen(
                        f"http://{self.host}:{rep.port}/ready",
                        timeout=2) as r:
                    if json.loads(r.read()).get("ready"):
                        return
            except Exception:  # noqa: BLE001 — still booting
                time.sleep(0.1)
        # kill the half-booted child: leaving it running would squat the
        # slot's port and leak a process (shutdown mid-boot lands here too,
        # so a closing manager never waits out the full ready timeout)
        self._kill(rep)
        raise TimeoutError(
            f"replica on :{rep.port} never became ready"
            + (" (shutdown requested)" if self._stop.is_set() else ""))

    def scale_to(self, n: int) -> int:
        """Grow/shrink to n replicas (the autoscaler's apply_fn).  Spawning
        (slow: process boot + ready poll) happens OUTSIDE the gateway lock
        so predict() keeps serving from live replicas meanwhile; the
        scale lock serializes concurrent resizes."""
        n = max(int(n), 0)
        with self._scale_lock:
            while self.live_count() < n:
                with self._lock:
                    slot = self._first_free_slot()
                    if slot == len(self.replicas):
                        self.replicas.append(None)  # reserve
                rep = self._spawn(slot)
                with self._lock:
                    self.replicas[slot] = rep
            victims = []
            with self._lock:
                while self.live_count() > n:
                    slot = max(i for i, r in enumerate(self.replicas)
                               if r is not None)
                    victims.append(self.replicas[slot])
                    self.replicas[slot] = None
            for victim in victims:
                self._kill(victim)
        return self.live_count()

    def _first_free_slot(self) -> int:
        # _lock is an RLock: scale_to calls this with it already held,
        # and taking it here keeps the scan safe for any future caller
        with self._lock:
            for i, r in enumerate(self.replicas):
                if r is None:
                    return i
            return len(self.replicas)

    @staticmethod
    def _kill(rep: _Replica) -> None:
        if rep.proc.poll() is None:
            rep.proc.terminate()
            try:
                rep.proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                rep.proc.kill()

    def live_count(self) -> int:
        # snapshot under the gateway lock: the monitor and scale threads
        # mutate the slot list concurrently
        with self._lock:
            return sum(1 for r in self.replicas
                       if r is not None and r.proc.poll() is None)

    def rolling_restart(self) -> None:
        """Restart replicas ONE AT A TIME (version rollout/rollback: each
        respawn loads the card's now-current version; the other slots keep
        serving).  The slot is retired (None) around the swap so the
        monitor can't double-spawn it."""
        with self._scale_lock:
            for slot in range(len(self.replicas)):
                with self._lock:
                    rep = self.replicas[slot]
                    if rep is None:
                        continue
                    self.replicas[slot] = None      # retire during swap
                self._kill(rep)
                try:
                    new = self._spawn(slot)
                except Exception:
                    # reinstall the (dead) old replica: the monitor loop
                    # retries DEAD slots every tick, so capacity heals
                    # once the card becomes loadable again — a None slot
                    # would be lost forever
                    with self._lock:
                        self.replicas[slot] = rep
                    raise
                new.restarts = rep.restarts + 1
                with self._lock:
                    self.replicas[slot] = new

    # -- self-healing monitor ----------------------------------------------
    def start_monitor(self) -> None:
        if self._monitor is None:
            self._monitor = threading.Thread(target=self._monitor_loop,
                                             daemon=True,
                                             name="replica-monitor")
            self._monitor.start()

    def _monitor_loop(self) -> None:
        while not self._stop.is_set():
            with self._lock:
                dead = [(slot, rep) for slot, rep in
                        enumerate(self.replicas)
                        if rep is not None and rep.proc.poll() is not None]
            for slot, rep in dead:
                logging.warning("replica[%d] died rc=%s — restarting",
                                slot, rep.proc.returncode)
                try:
                    # spawn outside the gateway lock: live replicas keep
                    # serving during the restart window
                    new = self._spawn(slot)
                except Exception:  # noqa: BLE001
                    # a failed restart (port stolen, card unloadable) must
                    # not kill the monitor — log and retry next tick
                    logging.exception("replica[%d] restart failed; will "
                                      "retry", slot)
                    continue
                new.restarts = rep.restarts + 1
                with self._lock:
                    # a concurrent scale_to shrink may have retired this
                    # slot (set it None) or replaced it while we were
                    # spawning; installing unconditionally would resurrect
                    # the slot and exceed the requested replica count
                    installed = (slot < len(self.replicas)
                                 and self.replicas[slot] is rep)
                    if installed:
                        self.replicas[slot] = new
                if not installed:
                    logging.info("replica[%d] retired during restart — "
                                 "discarding replacement", slot)
                    self._kill(new)
            self._stop.wait(self.monitor_interval_s)

    # -- gateway ------------------------------------------------------------
    def predict(self, payload: Dict[str, Any], timeout: float = 30.0
                ) -> Any:
        """Round-robin a request across live replicas (one retry on a
        replica that dies mid-request)."""
        for _ in range(2):
            with self._lock:
                live = [r for r in self.replicas
                        if r is not None and r.proc.poll() is None]
                if not live:
                    raise RuntimeError("no live replicas")
                rep = live[self._rr % len(live)]
                self._rr += 1
            try:
                req = urllib.request.Request(
                    f"http://{self.host}:{rep.port}/predict",
                    data=json.dumps(payload).encode(),
                    headers={"Content-Type": "application/json"})
                with urllib.request.urlopen(req, timeout=timeout) as r:
                    return json.loads(r.read())
            except Exception:  # noqa: BLE001 — retry once on another replica
                continue
        raise RuntimeError("predict failed on all tried replicas")

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {"live": self.live_count(),
                    "slots": len(self.replicas),
                    "restarts": sum(r.restarts for r in self.replicas
                                    if r is not None)}

    def shutdown(self) -> None:
        self._stop.set()
        if self._monitor is not None:
            self._monitor.join(timeout=5)
            if self._monitor.is_alive():
                logging.warning("replica monitor did not stop within 5s "
                                "(mid-spawn); it will exit on its next "
                                "tick")
            self._monitor = None
        # serialize with any in-flight scale_to/rolling_restart: their
        # _wait_ready aborts promptly on _stop, and killing/clearing the
        # slots under them would leak the replica they are about to
        # install
        with self._scale_lock:
            with self._lock:
                for rep in self.replicas:
                    if rep is not None:
                        self._kill(rep)
                self.replicas = []
