"""Vertical (feature-split) FL and SplitNN.

Capability parity:
 - `simulation/sp/classical_vertical_fl/` — two parties hold disjoint feature
   columns of the SAME rows; the guest (label holder) and host each run a
   bottom model producing logit contributions; only logits/gradients cross
   the party boundary, never raw features.
 - `simulation/mpi/split_nn/SplitNNAPI.py:25-29` — a network split at a cut
   layer: clients own the bottom, the server owns the top; activations flow
   up, gradients flow back.

TPU-first: each party's forward/backward is its own jit; the exchange is an
explicit function boundary (activations/grads as arrays), mirroring the wire
protocol while letting XLA optimize each side.
"""

from __future__ import annotations

import logging
from typing import Any, Dict, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import linen as nn

from ...core import mlops


class _PartyDense(nn.Module):
    features: int
    n_out: int = 1

    @nn.compact
    def __call__(self, x):
        h = nn.Dense(self.features)(x)
        h = nn.relu(h)
        return nn.Dense(self.n_out)(h)  # logit contribution(s)


class VerticalFLAPI:
    """Two-party classical VFL on a label-holder/host feature split.

    Binary datasets (adult, lending_club) keep the reference's logistic
    formulation (scalar logit sum + sigmoid BCE); multiclass datasets
    (NUS-WIDE, 5 classes) generalize to per-class logit contributions
    summed across parties + softmax CE — same wire contract (only
    logits/grad-of-logits cross the party boundary)."""

    def __init__(self, args: Any, device: Any, dataset: Tuple, bundle: Any):
        self.args = args
        (_, _, (x_tr, y_tr), (x_te, y_te), *_rest) = dataset
        class_num = int(_rest[-1]) if _rest else 2
        self.multiclass = class_num > 2
        n_out = class_num if self.multiclass else 1
        d = x_tr.shape[1]
        self.split = d // 2
        self.x_a, self.x_b = x_tr[:, :self.split], x_tr[:, self.split:]
        self.y = np.asarray(y_tr, np.int32 if self.multiclass else np.float32)
        self.xte_a, self.xte_b = x_te[:, :self.split], x_te[:, self.split:]
        self.yte = np.asarray(y_te,
                              np.int32 if self.multiclass else np.float32)

        hidden = int(getattr(args, "vfl_hidden", 32) or 32)
        self.party_a = _PartyDense(hidden, n_out)   # guest (holds labels)
        self.party_b = _PartyDense(hidden, n_out)   # host
        k = jax.random.PRNGKey(int(getattr(args, "random_seed", 0) or 0))
        ka, kb = jax.random.split(k)
        self.params_a = self.party_a.init(ka, jnp.zeros((1, self.split)))
        self.params_b = self.party_b.init(
            kb, jnp.zeros((1, d - self.split)))
        lr = float(getattr(args, "learning_rate", 0.03))
        self.tx = optax.sgd(lr)
        self.opt_a = self.tx.init(self.params_a)
        self.opt_b = self.tx.init(self.params_b)
        self.batch_size = int(getattr(args, "batch_size", 64))
        self.metrics_history: List[Dict[str, Any]] = []

        # party-local jitted steps; only logits/grad-of-logits cross parties
        multiclass = self.multiclass

        def _squeeze(logits):
            return logits if multiclass else logits[:, 0]

        @jax.jit
        def forward_a(params, x):
            return _squeeze(self.party_a.apply(params, x))

        @jax.jit
        def forward_b(params, x):
            return _squeeze(self.party_b.apply(params, x))

        @jax.jit
        def guest_loss_and_glogit(logit_sum, y):
            def f(ls):
                if multiclass:
                    return jnp.mean(
                        optax.softmax_cross_entropy_with_integer_labels(
                            ls, y))
                return jnp.mean(optax.sigmoid_binary_cross_entropy(ls, y))
            loss, g = jax.value_and_grad(f)(logit_sum)
            return loss, g

        from functools import partial

        @partial(jax.jit, static_argnums=(3,))
        def backward_party(params, x, g_logit, apply_fn_tag):
            # vjp of the party's logit w.r.t. its params given upstream grad
            def f(p):
                mod = self.party_a if apply_fn_tag == 0 else self.party_b
                return _squeeze(mod.apply(p, x))
            _, vjp = jax.vjp(f, params)
            return vjp(g_logit)[0]

        self._forward_a, self._forward_b = forward_a, forward_b
        self._guest = guest_loss_and_glogit
        self._backward = backward_party

    def train(self) -> Dict[str, Any]:
        epochs = int(self.args.comm_round)
        bs = self.batch_size
        n = len(self.y)
        final: Dict[str, Any] = {}
        for epoch in range(epochs):
            perm = np.random.RandomState(epoch).permutation(n)
            losses = []
            for s in range(0, n - bs + 1, bs):
                idx = perm[s:s + bs]
                xa = jnp.asarray(self.x_a[idx])
                xb = jnp.asarray(self.x_b[idx])
                y = jnp.asarray(self.y[idx])
                la = self._forward_a(self.params_a, xa)   # party A
                lb = self._forward_b(self.params_b, xb)   # party B → guest
                loss, g = self._guest(la + lb, y)          # guest computes
                ga = self._backward(self.params_a, xa, g, 0)
                gb = self._backward(self.params_b, xb, g, 1)
                ua, self.opt_a = self.tx.update(ga, self.opt_a)
                ub, self.opt_b = self.tx.update(gb, self.opt_b)
                self.params_a = optax.apply_updates(self.params_a, ua)
                self.params_b = optax.apply_updates(self.params_b, ub)
                losses.append(float(loss))
            acc = self._evaluate()
            final = {"test_acc": acc, "train_loss": float(np.mean(losses)),
                     "round": epoch,
                     "test_loss": float(np.mean(losses))}
            self.metrics_history.append(final)
            mlops.log(final)
            logging.info("VFL epoch %d: %s", epoch, final)
        return final

    def _evaluate(self) -> float:
        la = self._forward_a(self.params_a, jnp.asarray(self.xte_a))
        lb = self._forward_b(self.params_b, jnp.asarray(self.xte_b))
        if self.multiclass:
            pred = np.asarray(jnp.argmax(la + lb, axis=-1))
            return float((pred == self.yte).mean())
        pred = (np.asarray(la + lb) > 0).astype(np.float32)
        return float((pred == self.yte).mean())


class SplitNNAPI:
    """SplitNN: client bottom half + server top half; activations cross the
    cut (reference splits at layer 1).  Clients take turns (round-robin) as
    in the reference's sequential relay."""

    def __init__(self, args: Any, device: Any, dataset: Tuple, bundle: Any):
        self.args = args
        (_, _, (x_tr, y_tr), (x_te, y_te), local_num, train_local, test_local,
         class_num) = dataset
        self.train_local = train_local
        self.local_num = local_num
        self.x_te = np.asarray(x_te, np.float32).reshape(len(y_te), -1)
        self.y_te = np.asarray(y_te)
        self.class_num = int(class_num)
        d = self.x_te.shape[1]
        hidden = int(getattr(args, "split_hidden", 64) or 64)

        class Bottom(nn.Module):
            @nn.compact
            def __call__(self, x):
                return nn.relu(nn.Dense(hidden)(x))

        class Top(nn.Module):
            classes: int

            @nn.compact
            def __call__(self, h):
                h = nn.relu(nn.Dense(hidden)(h))
                return nn.Dense(self.classes)(h)

        self.bottom, self.top = Bottom(), Top(self.class_num)
        k = jax.random.PRNGKey(int(getattr(args, "random_seed", 0) or 0))
        kb, kt = jax.random.split(k)
        self.n_clients = int(args.client_num_in_total)
        self.bottom_params = [
            self.bottom.init(jax.random.fold_in(kb, c), jnp.zeros((1, d)))
            for c in range(self.n_clients)]
        self.top_params = self.top.init(kt, jnp.zeros((1, hidden)))
        lr = float(getattr(args, "learning_rate", 0.03))
        self.tx = optax.sgd(lr)
        self.opt_bottom = [self.tx.init(p) for p in self.bottom_params]
        self.opt_top = self.tx.init(self.top_params)
        self.batch_size = int(getattr(args, "batch_size", 32))
        self.metrics_history: List[Dict[str, Any]] = []

        @jax.jit
        def client_forward(bp, x):
            return self.bottom.apply(bp, x)

        @jax.jit
        def server_step(tp, acts, y):
            def f(p, a):
                logits = self.top.apply(p, a)
                logz = jax.nn.logsumexp(logits, axis=-1)
                gold = jnp.take_along_axis(
                    logits, y[:, None].astype(jnp.int32), axis=-1)[:, 0]
                return jnp.mean(logz - gold)
            (loss), grads = jax.value_and_grad(f, argnums=(0, 1))(tp, acts)
            return loss, grads[0], grads[1]  # loss, dTop, dActs

        @jax.jit
        def client_backward(bp, x, g_act):
            def f(p):
                return self.bottom.apply(p, x)
            _, vjp = jax.vjp(f, bp)
            return vjp(g_act)[0]

        self._cf, self._ss, self._cb = client_forward, server_step, \
            client_backward

    def train(self) -> Dict[str, Any]:
        rounds = int(self.args.comm_round)
        bs = self.batch_size
        final: Dict[str, Any] = {}
        for r in range(rounds):
            losses = []
            for cid in range(self.n_clients):  # relay order
                x, y = self.train_local[cid]
                x = np.asarray(x, np.float32).reshape(len(y), -1)
                for s in range(0, len(y) - bs + 1, bs):
                    xb = jnp.asarray(x[s:s + bs])
                    yb = jnp.asarray(np.asarray(y)[s:s + bs])
                    acts = self._cf(self.bottom_params[cid], xb)
                    loss, d_top, d_acts = self._ss(self.top_params, acts, yb)
                    d_bot = self._cb(self.bottom_params[cid], xb, d_acts)
                    ut, self.opt_top = self.tx.update(d_top, self.opt_top)
                    self.top_params = optax.apply_updates(self.top_params, ut)
                    ub, self.opt_bottom[cid] = self.tx.update(
                        d_bot, self.opt_bottom[cid])
                    self.bottom_params[cid] = optax.apply_updates(
                        self.bottom_params[cid], ub)
                    losses.append(float(loss))
                # relay: next client starts from previous client's bottom
                if cid + 1 < self.n_clients:
                    self.bottom_params[cid + 1] = self.bottom_params[cid]
                    self.opt_bottom[cid + 1] = self.opt_bottom[cid]
            acc = self._evaluate()
            final = {"test_acc": acc, "train_loss": float(np.mean(losses)),
                     "test_loss": float(np.mean(losses)), "round": r}
            self.metrics_history.append(final)
            logging.info("SplitNN round %d: %s", r, final)
        return final

    def _evaluate(self) -> float:
        acts = self._cf(self.bottom_params[-1], jnp.asarray(self.x_te))
        logits = self.top.apply(self.top_params, acts)
        pred = np.asarray(jnp.argmax(logits, axis=-1))
        return float((pred == self.y_te).mean())
