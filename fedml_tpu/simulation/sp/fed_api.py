"""SP (single-process) simulation — the Parrot sequential loop.

Capability parity: reference `simulation/sp/fedavg/fedavg_api.py:14-211`
(per-round client sampling with ``np.random.seed(round_idx)`` :127-136, local
train, weighted aggregate :144-159, periodic eval :110-121) generalized to
every federated optimizer the reference ships under `simulation/sp/*`:
FedAvg, FedOpt, FedProx, FedNova, FedDyn, SCAFFOLD, Mime.

Server-side algorithm state (FedOpt optimizer state, SCAFFOLD c_global,
FedDyn h, Mime momentum) is pure pytree math, jit-compiled where it counts.
"""

from __future__ import annotations

import logging
import time
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ...constants import (
    FED_OPT_FEDDYN,
    FED_OPT_FEDNOVA,
    FED_OPT_FEDOPT,
    FED_OPT_MIME,
    FED_OPT_SCAFFOLD,
)
from ...core import mlops
from ...core.alg_frame.context import Context
from ...ml.engine.optimizers import build_server_optimizer
from ...ml.trainer.default_trainer import (
    DefaultClientTrainer,
    DefaultServerAggregator,
)


def _tree_zeros_like(t):
    return jax.tree_util.tree_map(jnp.zeros_like, t)


class FedSimAPI:
    """One object drives the whole simulated federation."""

    def __init__(self, args: Any, device: Any, dataset: Tuple, bundle: Any,
                 client_trainer: Optional[DefaultClientTrainer] = None,
                 server_aggregator: Optional[DefaultServerAggregator] = None):
        self.args = args
        self.device = device
        (self.train_num, self.test_num, self.train_global, self.test_global,
         self.local_num_dict, self.train_data_local_dict,
         self.test_data_local_dict, self.class_num) = dataset
        self.bundle = bundle
        self.algo = str(getattr(args, "federated_optimizer", "FedAvg"))

        self.trainer = client_trainer or DefaultClientTrainer(bundle, args)
        self.aggregator = server_aggregator or DefaultServerAggregator(
            bundle, args)
        # robust aggregation rides FedMLAggOperator.agg unchanged (the
        # aggregator funnels through it); parse the selector NOW so a
        # typo'd --robust-agg fails at startup, not rounds in
        from ...ml.aggregator.robust import parse_robust_agg

        robust_spec = parse_robust_agg(getattr(args, "robust_agg", None))
        if robust_spec is not None:
            logging.info("sp: byzantine-robust aggregation enabled (%s)",
                         robust_spec)

        rng = jax.random.PRNGKey(int(getattr(args, "random_seed", 0) or 0))
        self.global_vars = bundle.init_variables(
            rng, batch_size=min(int(getattr(args, "batch_size", 32)), 8))
        self.aggregator.set_model_params(self.global_vars)

        # one batch geometry for every client → one compile (SURVEY §7 (b))
        bs = int(getattr(args, "batch_size", 32))
        max_n = max(self.local_num_dict.values()) if self.local_num_dict else bs
        self.num_batches = max(1, -(-int(max_n) // bs))
        self.trainer.set_num_batches(self.num_batches)

        # server-side algorithm state
        self.server_tx: Optional[optax.GradientTransformation] = None
        self.server_opt_state = None
        if self.algo == FED_OPT_FEDOPT:
            self.server_tx = build_server_optimizer(args)
            self.server_opt_state = self.server_tx.init(
                self.global_vars["params"])
        self.c_global = None
        self.c_locals: Dict[int, Any] = {}
        if self.algo == FED_OPT_SCAFFOLD:
            self.c_global = _tree_zeros_like(self.global_vars["params"])
        self.feddyn_h = None
        self.feddyn_lambdas: Dict[int, Any] = {}
        if self.algo == FED_OPT_FEDDYN:
            self.feddyn_h = _tree_zeros_like(self.global_vars["params"])
        self.mime_momentum = None
        if self.algo == FED_OPT_MIME:
            self.mime_momentum = _tree_zeros_like(self.global_vars["params"])

        self.metrics_history: List[Dict[str, Any]] = []

    # -- sampling (reference :127-136) --------------------------------------
    def _client_sampling(self, round_idx: int) -> List[int]:
        total = int(self.args.client_num_in_total)
        per_round = int(self.args.client_num_per_round)
        if total == per_round:
            return list(range(total))
        np.random.seed(round_idx)  # deliberate reference parity: reproducible
        return [int(c) for c in
                np.random.choice(range(total), per_round, replace=False)]

    # -- algorithm state plumbing -------------------------------------------
    def _algo_state_for(self, cid: int) -> Dict[str, Any]:
        if self.algo == FED_OPT_SCAFFOLD:
            if cid not in self.c_locals:
                self.c_locals[cid] = _tree_zeros_like(
                    self.global_vars["params"])
            return {"c_global": self.c_global, "c_local": self.c_locals[cid]}
        if self.algo == FED_OPT_FEDDYN:
            if cid not in self.feddyn_lambdas:
                self.feddyn_lambdas[cid] = _tree_zeros_like(
                    self.global_vars["params"])
            return {"feddyn_lambda": self.feddyn_lambdas[cid]}
        if self.algo == FED_OPT_MIME:
            return {"server_momentum": self.mime_momentum}
        return {}

    # -- the round loop ------------------------------------------------------
    def _local_train(self, cid: int, global_vars: Any = None
                     ) -> Tuple[float, Any]:
        """Full client lifecycle for one local round: dataset swap, param
        sync, before/after hooks (FHE dec/enc, local-DP noise — reference
        `core/alg_frame/client_trainer.py:59-82`), train.  Returns
        (n_samples, trained params)."""
        self.trainer.set_id(cid)
        self.trainer.update_dataset(
            self.train_data_local_dict[cid],
            self.test_data_local_dict[cid],
            self.local_num_dict[cid])
        self.trainer.set_model_params(
            self.global_vars if global_vars is None else global_vars)
        self.trainer.algo_state = self._algo_state_for(cid)
        self.trainer.on_before_local_training(
            self.trainer.local_train_dataset, self.device, self.args)
        self.trainer.train(self.trainer.local_train_dataset, self.device,
                           self.args)
        self.trainer.on_after_local_training(
            self.trainer.local_train_dataset, self.device, self.args)
        return float(self.local_num_dict[cid]), self.trainer.get_model_params()

    def _scaffold_leaked_start(self, first_cid: int):
        """Reference-leak reproduction (parity audits only): w0 advanced by
        ONE plain-SGD batch of the round's first client — the state the
        reference's w_global freezes at when the scaffold c-correction
        rebinds `param.data` after the first `optimizer.step()`
        (`ml/trainer/scaffold_trainer.py:147-170`)."""
        bs = int(getattr(self.args, "batch_size", 32))
        x, y = self.train_data_local_dict[first_cid]
        self.trainer.set_id(first_cid)
        self.trainer.update_dataset((x[:bs], y[:bs]), None, min(len(y), bs))
        self.trainer.set_model_params(self.global_vars)
        # round-0 correction term is c_global - c_local = 0 either way,
        # but pass fresh zero state for exactness
        self.trainer.algo_state = self._algo_state_for(first_cid)
        self.trainer.set_num_batches(1)
        self.trainer.train(self.trainer.local_train_dataset, self.device,
                           self.args)
        # restore the plane's FIXED batch grid (one geometry → one compile
        # for every client); None would re-derive nb per client and
        # recompile for every distinct client size
        self.trainer.set_num_batches(self.num_batches)
        return self.trainer.get_model_params()

    def train(self) -> Dict[str, Any]:
        comm_rounds = int(self.args.comm_round)
        final_metrics: Dict[str, Any] = {}
        for round_idx in range(comm_rounds):
            t0 = time.time()
            client_ids = self._client_sampling(round_idx)
            logging.info("round %d clients %s", round_idx, client_ids)
            results: List[Tuple[float, Any]] = []
            algo_outs: List[Tuple[int, float, Dict[str, Any]]] = []
            # Reference-bug compatibility (parity audits only): the
            # reference's round-0 `w_global = get_model_params()` returns a
            # state_dict ALIASING the live model tensors, so each
            # sequentially-trained client starts from the PREVIOUS client's
            # trained weights instead of the round's global model
            # (`simulation/sp/fedavg/fedavg_api.py:75-101`: deepcopy happens
            # per client on the mutated dict; rounds >= 1 aggregate into a
            # fresh dict, so only round 0 chains).  Root-caused in
            # benchmarks/parity_round0_oracle.py; see docs/PARITY.md.
            compat_scaffold = (self.algo == FED_OPT_SCAFFOLD and getattr(
                self.args, "scaffold_ref_bug_compat", False))
            chain_seq = (round_idx == 0 and bool(getattr(
                self.args, "fedavg_ref_chain_compat", False)))
            # Mime's reference re-aliases w_global to the shared model
            # EVERY round (`sp/mime/mime_trainer.py:123` rebinds w_global
            # to get_model_params() after the server step), so its
            # sequential clients chain in every round, not just round 0
            if getattr(self.args, "mime_ref_compat", False):
                chain_seq = True
            # SCAFFOLD's reference aliasing is different: its trainer's
            # c-correction REBINDS param.data each batch
            # (`ml/trainer/scaffold_trainer.py:166-170`), so w_global
            # freezes after the FIRST client's FIRST plain-SGD step; all
            # later round-0 clients start from w0 + that one step, and
            # from round 1 on nothing aliases at all.
            leaked: Any = None
            if (compat_scaffold and round_idx == 0
                    and len(client_ids) > 1):
                leaked = self._scaffold_leaked_start(client_ids[0])
            prev: Any = None
            self._compat_last_start = None
            with mlops.span("train", round_idx):
                for i, cid in enumerate(client_ids):
                    start: Any = None
                    if chain_seq:
                        start = prev
                    elif leaked is not None and i > 0:
                        start = leaked
                    n_k, params = self._local_train(cid, global_vars=start)
                    if chain_seq:
                        prev = params
                    self._compat_last_start = (start if start is not None
                                               else self.global_vars)
                    results.append((n_k, params))
                    algo_outs.append((cid, n_k, self.trainer.algo_out))

            # publish round context BEFORE aggregation so history-aware
            # defenses (foolsgold/crossround) and contribution assessment
            # see the correct client ids
            Context().add(Context.KEY_CLIENT_ID_LIST_IN_THIS_ROUND, client_ids)
            Context().add(Context.KEY_CLIENT_MODEL_LIST, results)

            with mlops.span("agg", round_idx):
                self.global_vars = self._server_update(
                    round_idx, client_ids, results, algo_outs)
                self.aggregator.set_model_params(self.global_vars)

            freq = int(getattr(self.args, "frequency_of_the_test", 5) or 5)
            if round_idx % freq == 0 or round_idx == comm_rounds - 1:
                metrics = self.aggregator.test(self.test_global, self.device,
                                               self.args)
                metrics["round"] = round_idx
                metrics["round_time"] = time.time() - t0
                ctx = Context()
                ctx.add(Context.KEY_METRICS_ON_LAST_ROUND,
                        self.metrics_history[-1] if self.metrics_history
                        else metrics)
                ctx.add(Context.KEY_METRICS_ON_AGGREGATED_MODEL, metrics)
                ctx.add(Context.KEY_TEST_DATA, self.test_global)
                if getattr(self.args, "contribution_alg", None):
                    self.aggregator.assess_contribution()
                self.metrics_history.append(metrics)
                final_metrics = metrics
                mlops.log({"round": round_idx, **metrics})
                logging.info("round %d: %s", round_idx, metrics)
            mlops.log_round_info(comm_rounds, round_idx)
        if getattr(self.args, "contribution_alg", None):
            final_metrics["contributions"] = (
                self.aggregator.final_contribution_assigned_by_shapley)
        return final_metrics

    # -- server aggregation per algorithm ------------------------------------
    def _server_update(self, round_idx: int, client_ids: List[int],
                       results: List[Tuple[float, Any]],
                       algo_outs: List[Tuple[int, float, Dict[str, Any]]]):
        if getattr(self.args, "feddyn_ref_bug_compat", False):
            # Reference-bug compatibility (parity audits only) for FedDyn's
            # SP trainer, reproducing THREE defects at once:
            # (a) the dynamic-regularization penalties are computed on
            #     `param.data` (`ml/trainer/feddyn_trainer.py:45-60`) so
            #     they contribute ZERO gradient — local training is plain
            #     SGD (run this compat with federated_optimizer=FedAvg);
            # (b) aggregation is an UNWEIGHTED SUM of client params
            #     (`ml/aggregator/agg_operator.py:68-78`), later divided
            #     by K, i.e. a uniform (not sample-weighted) average;
            # (c) `old_w_global = get_model_params()` at aggregation time
            #     ALIASES the shared model = the LAST client's trained
            #     weights (`sp/feddyn/feddyn_trainer.py:119-130`), not the
            #     round's start, so the h-state tracks a biased delta.
            # Server math verbatim: h -= a*(w_sum - w_last*K)/N;
            # w_next = w_sum/K - h.  Default FedDyn implements the paper.
            alpha = float(getattr(self.args, "feddyn_alpha", 0.01) or 0.01)
            k_count = float(len(results))
            n_total = float(self.args.client_num_in_total)
            if not hasattr(self, "_feddyn_ref_h"):
                self._feddyn_ref_h = jax.tree_util.tree_map(
                    jnp.zeros_like, self.global_vars)
            w_sum = jax.tree_util.tree_map(
                lambda *xs: sum(xs), *[p for _, p in results])
            w_last = results[-1][1]
            self._feddyn_ref_h = jax.tree_util.tree_map(
                lambda h, s, l: h - alpha * (s - l * k_count) / n_total,
                self._feddyn_ref_h, w_sum, w_last)
            return jax.tree_util.tree_map(
                lambda s, h: s / k_count - h, w_sum, self._feddyn_ref_h)

        compat_scaffold = (self.algo == FED_OPT_SCAFFOLD and getattr(
            self.args, "scaffold_ref_bug_compat", False))
        # compat mode bypasses aggregation entirely — don't run the
        # defense/filter hooks over results just to discard them
        raw = (None if compat_scaffold
               else self.aggregator.on_before_aggregation(results))

        if self.algo == FED_OPT_SCAFFOLD:
            n_total = float(self.args.client_num_in_total)
            if compat_scaffold:
                # Reference-bug compatibility (parity audits only), bit-
                # exact reproduction of THREE reference defects at once:
                # (a) aggregation computes a weighted sum then OVERWRITES
                #     it with the LAST client's delta
                #     (`ml/aggregator/agg_operator.py:100-118`), applying
                #     w_next = w_base + server_lr·Δ_last and
                #     c_global += c_delta_last / N;
                # (b) the base is the frozen ALIASED w_global — round 0:
                #     w0 + the first client's first SGD step (see
                #     _scaffold_leaked_start); rounds >= 1: the round
                #     start (`sp/scaffold/scaffold_trainer.py:81,131-137`);
                # (c) c_model_local is NEVER written back
                #     (`sp/scaffold/client.py:44-56` rebinds dict slots,
                #     not module params), so c_locals stay 0 — compat
                #     therefore skips the c_locals update.
                # Default path below is the deliberate FIX.
                server_lr = float(getattr(self.args, "server_lr", 1.0)
                                  or 1.0)
                _, last_params = results[-1]
                base = (self._compat_last_start
                        if getattr(self, "_compat_last_start", None)
                        is not None else self.global_vars)
                # w_next = w_global_frozen + server_lr·Δ_last, where
                # Δ_last = w_last_trained − start_last and start_last ==
                # the frozen w_global (all post-leak clients share it)
                new_vars = jax.tree_util.tree_map(
                    lambda s, w: s + (w - s) * server_lr,
                    base, last_params)
                self.c_global = jax.tree_util.tree_map(
                    lambda c, d: c + d / n_total, self.c_global,
                    algo_outs[-1][2]["c_delta"])
                return new_vars
            for cid, _, out in algo_outs:
                self.c_locals[cid] = out["c_local"]
            avg_vars = self.aggregator.aggregate(raw)
            if isinstance(avg_vars, tuple):  # not the SCAFFOLD pair path here
                avg_vars = avg_vars[0]
            c_deltas = [out["c_delta"] for _, _, out in algo_outs]
            delta_sum = jax.tree_util.tree_map(
                lambda *xs: sum(xs) / n_total, *c_deltas)
            self.c_global = jax.tree_util.tree_map(
                lambda c, d: c + d, self.c_global, delta_sum)
            new_vars = avg_vars
        elif self.algo == FED_OPT_FEDNOVA:
            weights = np.array([n for _, n, _ in algo_outs], np.float64)
            p = weights / weights.sum()
            taus = np.array([float(out["tau"]) for _, _, out in algo_outs])
            tau_eff = float((p * taus).sum())
            lr = float(self.args.learning_rate)
            ds = [out["nova_d"] for _, _, out in algo_outs]
            d_avg = jax.tree_util.tree_map(
                lambda *xs: sum(pk * x for pk, x in zip(p, xs)), *ds)
            params = jax.tree_util.tree_map(
                lambda w, d: w - tau_eff * lr * d,
                self.global_vars["params"], d_avg)
            avg_vars = self.aggregator.aggregate(raw)  # for model_state avg
            new_vars = dict(avg_vars, params=params)
        elif self.algo == FED_OPT_MIME:
            avg_vars = self.aggregator.aggregate(raw)
            grads = [(n, out["full_grad"]) for _, n, out in algo_outs]
            from ...ml.aggregator.agg_operator import weighted_average
            g = weighted_average(grads)
            beta = float(getattr(self.args, "server_momentum", 0.9) or 0.9)
            if getattr(self.args, "mime_ref_compat", False):
                # Reference-Mime server step (`sp/mime/mime_trainer.py:
                # 119-125` + OptRepo SGD): torch-SGD momentum on the
                # AVERAGED params with the averaged clipped full grads —
                # d = g + wd*w_avg; B <- sm*B + d; w <- w_avg -
                # server_lr*B.  (The published MimeLite keeps w = avg and
                # only updates the momentum state — the default below.)
                wd = float(getattr(self.args, "weight_decay", 0.0) or 0.0)
                server_lr = float(getattr(self.args, "server_lr", 1.0)
                                  or 1.0)
                d = jax.tree_util.tree_map(
                    lambda gg, w: gg + wd * w, g, avg_vars["params"])
                self.mime_momentum = jax.tree_util.tree_map(
                    lambda m, dd: beta * m + dd, self.mime_momentum, d)
                params = jax.tree_util.tree_map(
                    lambda w, m: w - server_lr * m,
                    avg_vars["params"], self.mime_momentum)
                new_vars = dict(avg_vars, params=params)
            else:
                self.mime_momentum = jax.tree_util.tree_map(
                    lambda m, gg: beta * m + (1.0 - beta) * gg,
                    self.mime_momentum, g)
                new_vars = avg_vars
        elif self.algo == FED_OPT_FEDDYN:
            for cid, _, out in algo_outs:
                self.feddyn_lambdas[cid] = out["feddyn_lambda"]
            alpha = float(getattr(self.args, "feddyn_alpha", 0.01) or 0.01)
            avg_vars = self.aggregator.aggregate(raw)
            m = float(len(results))
            n_total = float(self.args.client_num_in_total)
            delta = jax.tree_util.tree_map(
                lambda avg, g: (avg - g) * (m / n_total),
                avg_vars["params"], self.global_vars["params"])
            self.feddyn_h = jax.tree_util.tree_map(
                lambda h, d: h - alpha * d, self.feddyn_h, delta)
            params = jax.tree_util.tree_map(
                lambda avg, h: avg - h / alpha,
                avg_vars["params"], self.feddyn_h)
            new_vars = dict(avg_vars, params=params)
        elif self.algo == FED_OPT_FEDOPT and self.server_tx is not None:
            avg_vars = self.aggregator.aggregate(raw)
            pseudo_grad = jax.tree_util.tree_map(
                lambda g, a: g - a, self.global_vars["params"],
                avg_vars["params"])
            updates, self.server_opt_state = self.server_tx.update(
                pseudo_grad, self.server_opt_state,
                self.global_vars["params"])
            params = optax.apply_updates(self.global_vars["params"], updates)
            new_vars = dict(avg_vars, params=params)
        else:
            new_vars = self.aggregator.aggregate(raw)

        return self.aggregator.on_after_aggregation(new_vars)
