"""Split-knowledge-transfer, federated-GAN, and TurboAggregate planes.

Capability parity with reference `simulation/mpi/` algorithm families:
 - FedGKT          (`mpi/fedgkt/` — clients train a small edge model, the
   server trains a large head on client-extracted features; knowledge flows
   both ways via KL distillation)
 - FedGAN          (`mpi/fedgan/` — clients train a DCGAN locally; the server
   federated-averages BOTH generator and discriminator)
 - TurboAggregate  (`sp/turboaggregate/` — clients organized into a ring of
   groups; partial aggregates flow group-to-group, so no single party sees
   any individual update in the clear)

TPU-first: all client/server steps are jit-compiled scans over fixed-shape
padded batches (one compile per geometry); the distillation and GAN losses
are fused elementwise tails on the model matmuls.
"""

from __future__ import annotations

import logging
import time
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import linen as nn

from ...ml.aggregator.agg_operator import weighted_average
from ...ml.engine.local_update import make_batches
from ...models.gan import DCGANDiscriminator, DCGANGenerator
from .fed_api import FedSimAPI


# --------------------------------------------------------------------------
# FedGKT (reference mpi/fedgkt/: GKTClientTrainer/GKTServerTrainer)
# --------------------------------------------------------------------------
class GKTClientNet(nn.Module):
    """Edge-side: small conv extractor + local classifier head."""

    num_classes: int
    feat_dim: int = 64
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype)
        if x.ndim == 3:
            x = x[..., None]
        for f in (16, 32):
            x = nn.relu(nn.Conv(f, (3, 3), padding="SAME",
                                dtype=self.dtype)(x))
            x = nn.max_pool(x, (2, 2), strides=(2, 2))
        feat = nn.relu(nn.Dense(self.feat_dim, dtype=self.dtype)(
            x.reshape((x.shape[0], -1))))
        logits = nn.Dense(self.num_classes, dtype=self.dtype,
                          param_dtype=jnp.float32)(feat)
        return feat.astype(jnp.float32), logits.astype(jnp.float32)


class GKTServerNet(nn.Module):
    """Server-side large head over client features."""

    num_classes: int
    hidden: int = 256
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, feat, train: bool = False):
        h = feat.astype(self.dtype)
        for _ in range(2):
            h = nn.relu(nn.Dense(self.hidden, dtype=self.dtype)(h))
        return nn.Dense(self.num_classes, dtype=self.dtype,
                        param_dtype=jnp.float32)(h).astype(jnp.float32)


def _kl_to(teacher_logits, student_logits, temp: float = 3.0):
    t = jax.nn.softmax(teacher_logits / temp, axis=-1)
    ls = jax.nn.log_softmax(student_logits / temp, axis=-1)
    return -jnp.sum(t * ls, axis=-1) * temp * temp


def _masked_mean(per, mask):
    return jnp.sum(per * mask) / jnp.maximum(jnp.sum(mask), 1.0)


class FedGKTAPI(FedSimAPI):
    """Group knowledge transfer: per round, clients do local CE(+KL-to-server)
    epochs, upload (features, logits, labels); the server trains its head on
    the union with CE + KL-to-client, then returns per-client server logits
    for the next round's distillation."""

    def __init__(self, *a, **kw):
        super().__init__(*a, **kw)
        args = self.args
        ncls = int(self.class_num)
        self.client_net = GKTClientNet(num_classes=ncls)
        self.server_net = GKTServerNet(num_classes=ncls)
        rng = jax.random.PRNGKey(int(getattr(args, "random_seed", 0) or 0))
        c_rng, s_rng = jax.random.split(rng)  # one key per network init
        bs = int(getattr(args, "batch_size", 32))
        x0 = jnp.zeros((bs,) + self.bundle.input_shape, jnp.float32)
        self.client_params = self.client_net.init(c_rng, x0)
        feat0, _ = self.client_net.apply(self.client_params, x0)
        self.server_params = self.server_net.init(s_rng, feat0)
        lr = float(getattr(args, "learning_rate", 0.01) or 0.01)
        self.c_tx = optax.sgd(lr, momentum=0.9)
        self.s_tx = optax.adam(lr)
        self.s_opt = self.s_tx.init(self.server_params)
        self.kd_alpha = float(getattr(args, "kd_alpha", 0.5) or 0.5)
        self.server_logits: Dict[int, jnp.ndarray] = {}
        self._build_steps()

    def _build_steps(self):
        cnet, snet, a = self.client_net, self.server_net, self.kd_alpha

        def client_loss(params, batch, soft, has_soft):
            _, logits = cnet.apply(params, batch["x"])
            logz = jax.nn.logsumexp(logits, axis=-1)
            gold = jnp.take_along_axis(
                logits, batch["y"][..., None].astype(jnp.int32),
                axis=-1)[..., 0]
            ce = _masked_mean(logz - gold, batch["mask"])
            kl = _masked_mean(_kl_to(soft, logits), batch["mask"])
            return ce + has_soft * a * kl

        def client_epoch(params, opt_state, batches, soft, has_soft):
            def step(carry, i):
                p, o = carry
                b = jax.tree_util.tree_map(lambda v: v[i], batches)
                s = jax.tree_util.tree_map(lambda v: v[i], soft)
                g = jax.grad(client_loss)(p, b, s, has_soft)
                up, o = self.c_tx.update(g, o, p)
                return (optax.apply_updates(p, up), o), 0.0

            nb = batches["mask"].shape[0]
            (params, opt_state), _ = jax.lax.scan(
                step, (params, opt_state), jnp.arange(nb))
            return params, opt_state

        def server_loss(params, feat, y, soft, mask):
            logits = snet.apply(params, feat)
            logz = jax.nn.logsumexp(logits, axis=-1)
            gold = jnp.take_along_axis(
                logits, y[..., None].astype(jnp.int32), axis=-1)[..., 0]
            ce = _masked_mean(logz - gold, mask)
            kl = _masked_mean(_kl_to(soft, logits), mask)
            return ce + a * kl

        def server_step(params, opt_state, feat, y, soft, mask):
            g = jax.grad(server_loss)(params, feat, y, soft, mask)
            up, opt_state = self.s_tx.update(g, opt_state, params)
            return optax.apply_updates(params, up), opt_state

        self._client_epoch = jax.jit(client_epoch)
        self._server_step = jax.jit(server_step)
        self._client_fwd = jax.jit(
            lambda p, x: cnet.apply(p, x))
        self._server_fwd = jax.jit(lambda p, f: snet.apply(p, f))

    def train(self) -> Dict[str, Any]:
        args = self.args
        comm_rounds = int(args.comm_round)
        bs = int(getattr(args, "batch_size", 32))
        epochs = int(getattr(args, "epochs", 1) or 1)
        ncls = int(self.class_num)
        c_opts = {c: self.c_tx.init(self.client_params)
                  for c in range(int(args.client_num_in_total))}
        c_params = {c: self.client_params
                    for c in range(int(args.client_num_in_total))}
        final: Dict[str, Any] = {}
        for round_idx in range(comm_rounds):
            t0 = time.time()
            sampled = self._client_sampling(round_idx)
            feats, ys, clogits, masks = [], [], [], []
            for cid in sampled:
                x, y = self.train_data_local_dict[cid]
                batches = make_batches(x, y, bs, self.num_batches)
                soft = self.server_logits.get(
                    cid, jnp.zeros(batches["mask"].shape + (ncls,)))
                has = jnp.float32(cid in self.server_logits)
                for _ in range(epochs):
                    c_params[cid], c_opts[cid] = self._client_epoch(
                        c_params[cid], c_opts[cid], batches, soft, has)
                f, lg = self._client_fwd(
                    c_params[cid],
                    batches["x"].reshape((-1,) + batches["x"].shape[2:]))
                feats.append(f)
                ys.append(batches["y"].reshape(-1))
                clogits.append(lg)
                masks.append(batches["mask"].reshape(-1))
            # server: several epochs over the union of client features
            # (reference GKTServerTrainer trains `epochs_server` per round)
            server_epochs = int(getattr(self.args, "gkt_server_epochs", 5)
                                or 5)
            for _ in range(server_epochs):
                for f, y, lg, m in zip(feats, ys, clogits, masks):
                    self.server_params, self.s_opt = self._server_step(
                        self.server_params, self.s_opt, f, y, lg, m)
            # return fresh server logits per client (next-round distillation)
            for i, cid in enumerate(sampled):
                slg = self._server_fwd(self.server_params, feats[i])
                self.server_logits[cid] = slg.reshape(
                    (self.num_batches, bs, ncls))
            # clients also share their edge model (fedavg) so eval has one net
            self.client_params = weighted_average(
                [(float(self.local_num_dict[c]), c_params[c])
                 for c in sampled])
            for c in c_params:
                c_params[c] = self.client_params
            freq = int(getattr(args, "frequency_of_the_test", 5) or 5)
            if round_idx % freq == 0 or round_idx == comm_rounds - 1:
                final = self._evaluate(round_idx, time.time() - t0)
        return final

    def _evaluate(self, round_idx: int, dt: float) -> Dict[str, Any]:
        x, y = self.test_global
        bs = 256
        correct = n = 0
        loss_sum = 0.0
        for i in range(0, len(y), bs):
            f, _ = self._client_fwd(self.client_params,
                                    jnp.asarray(x[i:i + bs], jnp.float32))
            logits = self._server_fwd(self.server_params, f)
            yy = jnp.asarray(y[i:i + bs])
            logz = jax.nn.logsumexp(logits, axis=-1)
            gold = jnp.take_along_axis(
                logits, yy[:, None].astype(jnp.int32), axis=-1)[:, 0]
            loss_sum += float(jnp.sum(logz - gold))
            correct += int(jnp.sum(jnp.argmax(logits, -1) == yy))
            n += len(yy)
        metrics = {"test_acc": correct / max(n, 1),
                   "test_loss": loss_sum / max(n, 1),
                   "round": round_idx, "round_time": dt}
        self.metrics_history.append(metrics)
        logging.info("fedgkt round %d: %s", round_idx, metrics)
        return metrics


# --------------------------------------------------------------------------
# FedGAN (reference mpi/fedgan/)
# --------------------------------------------------------------------------
class FedGANAPI(FedSimAPI):
    """Each sampled client runs local DCGAN steps (alternating D/G); the
    server weighted-averages generator AND discriminator params."""

    def __init__(self, *a, **kw):
        super().__init__(*a, **kw)
        args = self.args
        shape = tuple(self.bundle.input_shape)
        if len(shape) != 3:
            shape = (32, 32, 3)
        self.latent = int(getattr(args, "gan_latent_dim", 64) or 64)
        self.gen = DCGANGenerator(out_shape=shape, latent_dim=self.latent)
        self.disc = DCGANDiscriminator()
        rng = jax.random.PRNGKey(int(getattr(args, "random_seed", 0) or 0))
        g_rng, d_rng = jax.random.split(rng)  # one key per network init
        z0 = jnp.zeros((2, self.latent))
        self.g_params = self.gen.init(g_rng, z0)
        x0 = self.gen.apply(self.g_params, z0)
        self.d_params = self.disc.init(d_rng, x0)
        lr = float(getattr(args, "learning_rate", 2e-4) or 2e-4)
        self.g_tx = optax.adam(lr, b1=0.5)
        self.d_tx = optax.adam(lr, b1=0.5)
        self._build_steps()

    def _build_steps(self):
        gen, disc = self.gen, self.disc

        def bce(logits, target):
            return jnp.mean(jnp.maximum(logits, 0) - logits * target
                            + jnp.log1p(jnp.exp(-jnp.abs(logits))))

        def d_loss(dp, gp, x_real, z):
            fake = gen.apply(gp, z)
            lr_ = disc.apply(dp, x_real)
            lf = disc.apply(dp, fake)
            return bce(lr_, jnp.ones_like(lr_)) + bce(lf, jnp.zeros_like(lf))

        def g_loss(gp, dp, z):
            lf = disc.apply(dp, gen.apply(gp, z))
            return bce(lf, jnp.ones_like(lf))

        def local_steps(gp, dp, g_opt, d_opt, batches, rng):
            def step(carry, i):
                gp, dp, go, do, rng = carry
                rng, k1, k2 = jax.random.split(rng, 3)
                x = batches["x"][i] * 2.0 - 1.0  # [0,1] → [-1,1]
                z = jax.random.normal(k1, (x.shape[0], self.latent))
                dl, dg = jax.value_and_grad(d_loss)(dp, gp, x, z)
                up, do = self.d_tx.update(dg, do, dp)
                dp = optax.apply_updates(dp, up)
                z2 = jax.random.normal(k2, (x.shape[0], self.latent))
                gl, gg = jax.value_and_grad(g_loss)(gp, dp, z2)
                up, go = self.g_tx.update(gg, go, gp)
                gp = optax.apply_updates(gp, up)
                return (gp, dp, go, do, rng), (dl, gl)

            nb = batches["mask"].shape[0]
            (gp, dp, g_opt, d_opt, _), (dls, gls) = jax.lax.scan(
                step, (gp, dp, g_opt, d_opt, rng), jnp.arange(nb))
            return gp, dp, g_opt, d_opt, dls[-1], gls[-1]

        self._local_steps = jax.jit(local_steps)

    def train(self) -> Dict[str, Any]:
        args = self.args
        bs = int(getattr(args, "batch_size", 32))
        rng = jax.random.PRNGKey(1234)
        final: Dict[str, Any] = {}
        for round_idx in range(int(args.comm_round)):
            t0 = time.time()
            sampled = self._client_sampling(round_idx)
            g_results, d_results = [], []
            d_last = g_last = 0.0
            for cid in sampled:
                x, y = self.train_data_local_dict[cid]
                batches = make_batches(x, y, bs, self.num_batches)
                rng, sub = jax.random.split(rng)
                g_opt = self.g_tx.init(self.g_params)
                d_opt = self.d_tx.init(self.d_params)
                gp, dp, _, _, dl, gl = self._local_steps(
                    self.g_params, self.d_params, g_opt, d_opt, batches, sub)
                w = float(self.local_num_dict[cid])
                g_results.append((w, gp))
                d_results.append((w, dp))
                d_last, g_last = float(dl), float(gl)
            self.g_params = weighted_average(g_results)
            self.d_params = weighted_average(d_results)
            final = {"round": round_idx, "d_loss": d_last, "g_loss": g_last,
                     "round_time": time.time() - t0}
            self.metrics_history.append(final)
            logging.info("fedgan round %d: %s", round_idx, final)
        return final

    def generate(self, n: int = 8, seed: int = 0) -> np.ndarray:
        z = jax.random.normal(jax.random.PRNGKey(seed), (n, self.latent))
        return np.asarray(self.gen.apply(self.g_params, z))


# --------------------------------------------------------------------------
# TurboAggregate (reference sp/turboaggregate/)
# --------------------------------------------------------------------------
class TurboAggregateAPI(FedSimAPI):
    """Ring-of-groups aggregation: clients are organized into ``ta_group_num``
    groups arranged in a ring; each group adds its members' weighted updates
    to the running partial sum and forwards it, so individual updates are
    only ever seen inside a group (the reference adds Lagrange-coded
    redundancy for dropout tolerance; here dropout tolerance comes from the
    groups re-weighting by actually-contributed sample counts)."""

    def train(self) -> Dict[str, Any]:
        args = self.args
        comm_rounds = int(args.comm_round)
        n_groups = int(getattr(args, "ta_group_num", 2) or 2)
        final: Dict[str, Any] = {}
        for round_idx in range(comm_rounds):
            t0 = time.time()
            sampled = self._client_sampling(round_idx)
            groups = [sampled[i::n_groups] for i in range(n_groups)]
            partial = None  # running (unnormalized) sum flowing on the ring
            total_w = 0.0
            for members in groups:
                group_sum = None
                for cid in members:
                    w, params = self._local_train(cid)
                    contrib = jax.tree_util.tree_map(
                        lambda p: p * w, params)
                    group_sum = contrib if group_sum is None else \
                        jax.tree_util.tree_map(jnp.add, group_sum, contrib)
                    total_w += w
                if group_sum is not None:
                    partial = group_sum if partial is None else \
                        jax.tree_util.tree_map(jnp.add, partial, group_sum)
            self.global_vars = jax.tree_util.tree_map(
                lambda s: s / max(total_w, 1.0), partial)
            self.aggregator.set_model_params(self.global_vars)
            freq = int(getattr(args, "frequency_of_the_test", 5) or 5)
            if round_idx % freq == 0 or round_idx == comm_rounds - 1:
                metrics = self.aggregator.test(self.test_global, self.device,
                                               self.args)
                metrics.update(round=round_idx, round_time=time.time() - t0)
                self.metrics_history.append(metrics)
                final = metrics
                logging.info("turboaggregate round %d: %s", round_idx,
                             metrics)
        return final


# --------------------------------------------------------------------------
# FedAvg_seq (reference mpi/fedavg_seq/ — heterogeneity-aware scheduling)
# --------------------------------------------------------------------------
class FedAvgSeqAPI(FedSimAPI):
    """Sequential FedAvg with the heterogeneity-aware scheduler (reference
    `mpi/fedavg_seq/FedAVGAggregator.py:126-160`): the server records
    per-(worker, client) runtimes, fits linear per-worker cost models
    (`t_sample_fit`), and solves a min-makespan assignment of the sampled
    clients onto ``worker_num`` simulated workers; each worker then trains
    its clients sequentially.  The schedule and estimated makespan are
    surfaced in the round metrics."""

    def __init__(self, *a, **kw):
        super().__init__(*a, **kw)
        self.worker_num = int(getattr(self.args, "worker_num", 2) or 2)
        self.runtime_history: Dict[Tuple[int, int], List[Tuple[float, float]]] = {}

    def train(self) -> Dict[str, Any]:
        from ...core.schedule.seq_train_scheduler import (
            SeqTrainScheduler,
            t_sample_fit,
        )

        args = self.args
        comm_rounds = int(args.comm_round)
        final: Dict[str, Any] = {}
        for round_idx in range(comm_rounds):
            t0 = time.time()
            sampled = self._client_sampling(round_idx)
            workloads = [float(self.local_num_dict[c]) for c in sampled]
            fits = t_sample_fit(self.runtime_history) \
                if self.runtime_history else {}
            sched = SeqTrainScheduler(
                workloads, constraints=[1.0] * self.worker_num,
                fit_params=fits)
            assign, loads = sched.DP_schedule()
            results: List[Tuple[float, Any]] = []
            for worker, slots in enumerate(assign):
                for slot in slots:           # sequential per worker
                    cid = sampled[slot]
                    tc0 = time.time()
                    results.append(self._local_train(cid))
                    self.runtime_history.setdefault(
                        (worker, cid), []).append(
                        (float(self.local_num_dict[cid]),
                         time.time() - tc0))
            self.global_vars = weighted_average(results)
            self.aggregator.set_model_params(self.global_vars)
            freq = int(getattr(args, "frequency_of_the_test", 5) or 5)
            if round_idx % freq == 0 or round_idx == comm_rounds - 1:
                metrics = self.aggregator.test(self.test_global, self.device,
                                               self.args)
                metrics.update(round=round_idx, round_time=time.time() - t0,
                               schedule=[[int(sampled[s]) for s in slots]
                                         for slots in assign],
                               est_makespan=float(max(loads)))
                self.metrics_history.append(metrics)
                final = metrics
                logging.info("fedavg_seq round %d: %s", round_idx, metrics)
        return final
