"""SP-plane algorithm variants beyond the core optimizer set.

Capability parity with reference `simulation/sp/` & `simulation/mpi/`:
 - HierarchicalFL  (`sp/hierarchical_fl/` — client→group→global averaging)
 - Decentralized   (`sp/decentralized/`, `mpi/decentralized_framework/` —
   topology-driven neighbor gossip)
 - Async FedAvg    (`mpi/async_fedavg/` — staleness-weighted server updates)
 - VerticalFL      (`sp/classical_vertical_fl/` — two-party split features)
 - SplitNN         (`mpi/split_nn/` — model split at a cut layer)

All built on the same jitted engine; decentralized mixing is one
mixing-matrix contraction per round (MXU), not per-neighbor messaging.
"""

from __future__ import annotations

import logging
import time
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ...core import mlops
from ...core.distributed.topology import SymmetricTopologyManager
from ...ml.aggregator.agg_operator import weighted_average
from ...ml.engine.local_update import build_eval_step, build_local_update, make_batches
from .fed_api import FedSimAPI


class HierarchicalFLAPI(FedSimAPI):
    """Two-level FedAvg (reference `sp/hierarchical_fl/trainer.py`):
    ``group_num`` groups; each global round runs ``group_comm_round`` rounds
    of intra-group FedAvg before groups average globally."""

    def __init__(self, *a, **kw):
        super().__init__(*a, **kw)
        self.group_num = int(getattr(self.args, "group_num", 2) or 2)
        self.group_comm_round = int(
            getattr(self.args, "group_comm_round", 2) or 2)
        ids = list(range(int(self.args.client_num_in_total)))
        self.groups = [ids[i::self.group_num] for i in range(self.group_num)]

    def train(self) -> Dict[str, Any]:
        comm_rounds = int(self.args.comm_round)
        final = {}
        for round_idx in range(comm_rounds):
            t0 = time.time()
            group_models: List[Tuple[float, Any]] = []
            for gid, members in enumerate(self.groups):
                group_vars = self.global_vars
                for _ in range(self.group_comm_round):
                    results = [self._local_train(cid, group_vars)
                               for cid in members]
                    group_vars = weighted_average(results)
                n_group = float(sum(self.local_num_dict[c] for c in members))
                group_models.append((n_group, group_vars))
            self.global_vars = weighted_average(group_models)
            self.aggregator.set_model_params(self.global_vars)
            freq = int(getattr(self.args, "frequency_of_the_test", 5) or 5)
            if round_idx % freq == 0 or round_idx == comm_rounds - 1:
                metrics = self.aggregator.test(self.test_global, self.device,
                                               self.args)
                metrics.update(round=round_idx, round_time=time.time() - t0)
                self.metrics_history.append(metrics)
                final = metrics
                mlops.log(metrics)
                logging.info("hierarchical round %d: %s", round_idx, metrics)
        return final


class DecentralizedFLAPI(FedSimAPI):
    """Gossip FL over a symmetric topology: every client trains locally, then
    params mix with the row-stochastic matrix W (one contraction)."""

    def __init__(self, *a, **kw):
        super().__init__(*a, **kw)
        n = int(self.args.client_num_in_total)
        topo = SymmetricTopologyManager(
            n, int(getattr(self.args, "topology_neighbor_num", 2) or 2))
        topo.generate_topology()
        self.W = jnp.asarray(topo.get_mixing_matrix(), jnp.float32)
        self.client_vars = [self.global_vars for _ in range(n)]

    def train(self) -> Dict[str, Any]:
        comm_rounds = int(self.args.comm_round)
        n = int(self.args.client_num_in_total)
        final = {}
        for round_idx in range(comm_rounds):
            t0 = time.time()
            for cid in range(n):
                _, self.client_vars[cid] = self._local_train(
                    cid, self.client_vars[cid])
            # mix: stacked leading axis contraction with W
            stacked = jax.tree_util.tree_map(
                lambda *xs: jnp.stack(xs), *self.client_vars)
            mixed = jax.tree_util.tree_map(
                lambda s: jnp.tensordot(self.W, s, axes=1), stacked)
            self.client_vars = [
                jax.tree_util.tree_map(lambda s, i=i: s[i], mixed)
                for i in range(n)]
            # consensus model for eval = uniform average
            self.global_vars = jax.tree_util.tree_map(
                lambda s: jnp.mean(s, axis=0), mixed)
            self.aggregator.set_model_params(self.global_vars)
            freq = int(getattr(self.args, "frequency_of_the_test", 5) or 5)
            if round_idx % freq == 0 or round_idx == comm_rounds - 1:
                metrics = self.aggregator.test(self.test_global, self.device,
                                               self.args)
                metrics.update(round=round_idx, round_time=time.time() - t0)
                self.metrics_history.append(metrics)
                final = metrics
                logging.info("decentralized round %d: %s", round_idx, metrics)
        return final


class AsyncFedAvgAPI(FedSimAPI):
    """Async FedAvg (reference `mpi/async_fedavg/`): clients finish at
    heterogeneous times; the server applies each update immediately with
    staleness discount  w ← (1−α_s)·w + α_s·w_i,  α_s = α/(t − τ_i + 1)."""

    def train(self) -> Dict[str, Any]:
        comm_rounds = int(self.args.comm_round)
        n = int(self.args.client_num_in_total)
        alpha = float(getattr(self.args, "async_alpha", 0.6) or 0.6)
        rng = np.random.RandomState(
            int(getattr(self.args, "random_seed", 0) or 0))
        # simulated per-client speed: duration ~ U[1, 3] rounds
        duration = rng.uniform(1.0, 3.0, size=n)
        # event queue: (finish_time, client, model_version_when_started)
        events = sorted(
            [(duration[c], c, 0) for c in range(n)])
        server_step = 0
        final = {}
        t_end = float(comm_rounds)
        while events and events[0][0] <= t_end:
            finish_t, cid, tau = events.pop(0)
            _, w_i = self._local_train(cid)
            staleness = max(server_step - tau, 0)
            a = alpha / (staleness + 1.0)
            self.global_vars = jax.tree_util.tree_map(
                lambda g, wi: (1.0 - a) * g + a * wi, self.global_vars, w_i)
            server_step += 1
            # client starts again
            import bisect

            bisect.insort(events,
                          (finish_t + duration[cid], cid, server_step))
        self.aggregator.set_model_params(self.global_vars)
        metrics = self.aggregator.test(self.test_global, self.device,
                                       self.args)
        metrics["server_steps"] = server_step
        self.metrics_history.append(metrics)
        final = metrics
        logging.info("async fedavg done (%d updates): %s", server_step,
                     metrics)
        return final
