"""Parrot-TPU — vectorized federated simulation.

Capability parity: reference `simulation/mpi/` + `simulation/nccl/` (SURVEY
§2.4) — scaling simulated clients over hardware.  The reference does it with
MPI worker processes and NCCL reduce; this build does it the TPU way
(SURVEY §7 step 4):

* The WHOLE ROUND is one jit-compiled function: gather the sampled clients'
  padded batches from the device-resident dataset (XLA gather, no host
  transfer), ``vmap`` the local-update engine over the client axis, and
  aggregate with a fused weighted reduction (`agg_stacked`).
* Per-client algorithm state (SCAFFOLD control variates, FedDyn lambdas) is a
  stacked leading-axis pytree, gathered/scattered by client id inside the
  same jit.
* ``use_mesh=True`` shards the client axis over the `clients` mesh axis with
  ``with_sharding_constraint``; XLA lowers the aggregation sum to psum-style
  collectives over ICI — the NCCL-allreduce equivalent
  (`simulation/nccl/.../LocalAggregator.py:69-80`) with zero manual
  communication code.

Host work per round: sampling client ids (numpy, reference-parity seeding)
and logging.  Everything else stays in HBM.
"""

from __future__ import annotations

import contextlib
import logging
import os
import threading
import time
from functools import partial
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ...constants import (
    AXIS_CLIENTS,
    FED_OPT_FEDDYN,
    FED_OPT_FEDNOVA,
    FED_OPT_FEDOPT,
    FED_OPT_MIME,
    FED_OPT_SCAFFOLD,
)
from ...core import mlops
from ...core.mlops import flight_recorder, ledger
from ...core.mlops.lock_profiler import named_lock
from ...ml.aggregator.agg_operator import agg_stacked
from ...ml.aggregator.robust import parse_robust_agg, robust_agg_stacked
from ...ops import epilogue as _epilogue
from ...ml.engine.local_update import build_eval_step, build_local_update, make_batches
from ...ml.engine.mesh import MeshManager, build_hybrid_mesh, build_mesh
from ...ml.engine.optimizers import build_server_optimizer
from jax.sharding import NamedSharding, PartitionSpec as P


def bucket_plan(sizes: np.ndarray, k: int, bs: int, n_buckets: int,
                cap_ratio: float = 0.0) -> List[Dict[str, Any]]:
    """Pure size-bucket policy — shared by ``ParrotAPI._build_buckets``,
    bench.py's per-bucket waste report and the PERF003 padding-waste lint.

    Clients sort by size into ``B`` equal-count strata (B snapped to a
    divisor of ``k`` so quotas stay equal — every client's inclusion
    probability is exactly k/N).  Each stratum's batch capacity is

    * ``cap_ratio == 0``: ``nb = ceil(max_size_in_stratum / bs)`` — every
      sampled client runs its full local epoch (reference semantics), at
      the cost of padding every stratum to its LARGEST member.
    * ``cap_ratio > 0``:  ``nb = ceil(cap_ratio·mean_size / bs)`` (capped
      at the full capacity) — clients above the cap run a per-round
      ROTATING window of ``nb·bs`` of their samples instead of a full
      epoch, so padded compute tracks the stratum's mean, not its max.
      Coverage is preserved across rounds (the window start is uniform
      per round) and aggregation weights still use full sample counts.

    Returns one dict per stratum: ``members`` (client ids, size-sorted),
    ``q`` (clients sampled per round), ``nb`` (compute batch capacity),
    ``nb_full`` (capacity covering the largest member — the index-matrix
    width rotation addresses into), ``padded`` (q·nb·bs slots per round)
    and ``real`` (q·E[min(size, nb·bs)] expected real samples per round).
    """
    sizes = np.asarray(sizes)
    n_total = int(sizes.shape[0])
    divisors = [d for d in range(1, int(k) + 1)
                if int(k) % d == 0 and d <= n_total]
    b_eff = min(divisors, key=lambda d: (abs(d - int(n_buckets)), -d))
    order = np.argsort(sizes, kind="stable")
    groups = [g for g in np.array_split(order, b_eff) if len(g)]
    q = int(k) // len(groups)
    plan = []
    for g in groups:
        gsz = sizes[g]
        nb_full = max(1, -(-int(gsz.max()) // int(bs)))
        nb = nb_full
        if cap_ratio and cap_ratio > 0:
            cap = max(1, int(round(float(cap_ratio) * float(gsz.mean()))))
            nb = min(nb_full, max(1, -(-cap // int(bs))))
        quota = int(min(q, len(g)))
        capn = nb * int(bs)
        plan.append({
            "members": g.astype(np.int64),
            "q": quota,
            "nb": nb,
            "nb_full": nb_full,
            "padded": quota * capn,
            "real": float(quota * np.minimum(gsz, capn).mean()),
        })
    return plan


# ---------------------------------------------------------------------------
# Shared round-engine pieces.  ParrotAPI (device-resident dataset) and the
# hyper-scale streaming path (simulation/parrot/hyperscale.py — host-assembled
# cohorts, population too large for HBM) run the SAME per-cohort arithmetic:
# vmapped local updates over a stacked client axis, per-algorithm server-state
# handling, fused weighted aggregation.  These module-level functions are that
# shared contract; the two APIs differ only in how the batch grids reach the
# device.
# ---------------------------------------------------------------------------

def per_client_algo_state(algo: str, server_state: Dict[str, Any],
                          client_ids) -> Dict[str, Any]:
    """Per-cohort gather of the per-client algorithm state (SCAFFOLD
    variates, FedDyn lambdas) from the stacked ``[N, ...]`` server tables.
    Runs inside the round jit — when the tables are laid out sharded along
    the client axis, XLA lowers this to the cross-device cohort gather."""
    if algo == FED_OPT_SCAFFOLD:
        return {
            "c_global": server_state["c_global"],
            "c_local": jax.tree_util.tree_map(
                lambda t: t[client_ids], server_state["c_locals"]),
        }
    if algo == FED_OPT_FEDDYN:
        return {"feddyn_lambda": jax.tree_util.tree_map(
            lambda t: t[client_ids], server_state["lambdas"])}
    if algo == FED_OPT_MIME:
        return {"server_momentum": server_state["momentum"]}
    return {}


def algo_in_axes(algo: str):
    """vmap in_axes for the algo_state argument of ``local_update``."""
    return {
        FED_OPT_SCAFFOLD: {"c_global": None, "c_local": 0},
        FED_OPT_FEDDYN: {"feddyn_lambda": 0},
        FED_OPT_MIME: {"server_momentum": None},
    }.get(algo)


def grid_sharding(mesh, k_b: int, bs: int) -> Optional[NamedSharding]:
    """How a ``[K, nb, bs, ...]`` batch grid shards over the mesh.

    Prefer the client axis (pure client parallelism, aggregation lowers
    to one all-reduce over the mesh).  When a cohort/bucket quota K is
    smaller than the mesh, shard the INTRA-BATCH axis instead: each
    client's SGD step becomes data-parallel over devices and XLA inserts
    the gradient all-reduce.  Falls back to replicated (None) when
    neither axis divides the mesh.  Balanced layouts first (exact
    divisibility on either axis), then UNEVEN sharding (GSPMD pads the
    ragged shard) — never silently replicate while an axis is at least
    mesh-sized."""
    if mesh is None:
        return None
    names = tuple(mesh.axis_names)
    msize = int(np.prod([mesh.shape[n] for n in names]))
    if msize <= 1:
        return None
    if k_b % msize == 0:
        return NamedSharding(mesh, P(names))
    if bs % msize == 0:
        return NamedSharding(mesh, P(None, None, names))
    if k_b >= msize:
        return NamedSharding(mesh, P(names))
    if bs >= msize:
        return NamedSharding(mesh, P(None, None, names))
    logging.warning(
        "parrot mesh: clients-per-step %d and batch_size %d are both "
        "smaller than the %d-device mesh — running replicated", k_b,
        bs, msize)
    return None


def stacked_client_sharding(mesh) -> Optional[NamedSharding]:
    """Leading-axis sharding for ``[N, ...]`` per-client state tables:
    the client axis spreads over EVERY mesh axis so state capacity scales
    with chips instead of replicating N copies of the table."""
    if mesh is None:
        return None
    names = tuple(mesh.axis_names)
    if int(np.prod([mesh.shape[n] for n in names])) <= 1:
        return None
    return NamedSharding(mesh, P(names))


def build_aggregate(args: Any, algo: str, n_total: int,
                    server_tx: Any = None):
    """Shared post-vmap logic: weighted aggregation + per-algorithm
    server-state update, operating on stacked per-client outputs (the
    uniform round, the bucketed round and the hyper-scale streaming round
    all feed the same contract).

    ``robust_agg`` swaps the fused weighted mean for a stacked robust
    operator (`ml/aggregator/robust.py`) INSIDE the same jit — the
    per-client outputs already carry the leading client axis the robust
    kernels consume, so byzantine-robust rounds cost one fused
    sort/distance reduction, not a host round-trip."""
    robust_spec = parse_robust_agg(getattr(args, "robust_agg", None))
    # FedOpt's server step fuses into the epilogue kernel when the
    # optimizer maps onto a fused channel (sgd/momentum/adam): the params
    # subtree runs reduce → pseudo-grad → optimizer → cast in ONE pass
    # per leaf instead of reduce + optax update + apply.  Robust rounds
    # keep the optax path (the sort/distance center can't fuse).
    fused_opt = (_epilogue.spec_from_args(args)
                 if algo == FED_OPT_FEDOPT and robust_spec is None
                 else None)

    def aggregate(global_vars, server_state, client_ids, new_vars,
                  algo_out, metrics, weights):
        agg_vars = (robust_agg_stacked(robust_spec, new_vars, weights,
                                       center=global_vars)
                    if robust_spec is not None
                    else agg_stacked(new_vars, weights))
        new_state = dict(server_state)

        if algo == FED_OPT_FEDOPT and fused_opt is not None:
            # the plain params reduce above is dead code under the fused
            # channel (XLA DCEs it): the epilogue re-reads the stacked
            # params and emits the post-optimizer global directly
            params, opt_state = _epilogue.fused_epilogue(
                global_vars["params"], new_vars["params"], weights,
                1.0, fused_opt, server_state["opt_state"])
            agg_vars = dict(agg_vars, params=params)
            new_state["opt_state"] = opt_state
        elif algo == FED_OPT_FEDOPT:
            pseudo = jax.tree_util.tree_map(
                lambda g, a: g - a, global_vars["params"],
                agg_vars["params"])
            updates, opt_state = server_tx.update(
                pseudo, server_state["opt_state"], global_vars["params"])
            params = optax.apply_updates(global_vars["params"], updates)
            agg_vars = dict(agg_vars, params=params)
            new_state["opt_state"] = opt_state
        elif algo == FED_OPT_SCAFFOLD:
            new_state["c_locals"] = jax.tree_util.tree_map(
                lambda all_c, new_c: all_c.at[client_ids].set(new_c),
                server_state["c_locals"], algo_out["c_local"])
            delta = jax.tree_util.tree_map(
                lambda d: jnp.sum(d, axis=0) / float(n_total),
                algo_out["c_delta"])
            new_state["c_global"] = jax.tree_util.tree_map(
                lambda c, d: c + d, server_state["c_global"], delta)
        elif algo == FED_OPT_FEDDYN:
            alpha = float(getattr(args, "feddyn_alpha", 0.01) or 0.01)
            new_state["lambdas"] = jax.tree_util.tree_map(
                lambda all_l, new_l: all_l.at[client_ids].set(new_l),
                server_state["lambdas"], algo_out["feddyn_lambda"])
            m_frac = client_ids.shape[0] / float(n_total)
            new_state["h"] = jax.tree_util.tree_map(
                lambda h, avg, g: h - alpha * m_frac * (avg - g),
                server_state["h"], agg_vars["params"],
                global_vars["params"])
            agg_vars = dict(agg_vars, params=jax.tree_util.tree_map(
                lambda p, h: p - h / alpha, agg_vars["params"],
                new_state["h"]))
        elif algo == FED_OPT_FEDNOVA:
            w = weights / jnp.maximum(jnp.sum(weights), 1e-12)
            tau_eff = jnp.sum(w * algo_out["tau"])
            lr = float(getattr(args, "learning_rate", 0.03))
            d_avg = jax.tree_util.tree_map(
                lambda d: jnp.tensordot(w, d, axes=1), algo_out["nova_d"])
            agg_vars = dict(agg_vars, params=jax.tree_util.tree_map(
                lambda p, d: p - tau_eff * lr * d,
                global_vars["params"], d_avg))
        elif algo == FED_OPT_MIME:
            beta = float(getattr(args, "server_momentum", 0.9) or 0.9)
            # robust reduce the full grads too: poisoned grads corrupt
            # the server momentum just as poisoned params corrupt w
            g = (robust_agg_stacked(robust_spec,
                                    algo_out["full_grad"], weights)
                 if robust_spec is not None
                 else agg_stacked(algo_out["full_grad"], weights))
            new_state["momentum"] = jax.tree_util.tree_map(
                lambda m, gg: beta * m + (1.0 - beta) * gg,
                server_state["momentum"], g)

        round_metrics = {
            "train_loss": jnp.sum(metrics["train_loss"] * weights)
            / jnp.maximum(jnp.sum(weights), 1e-12),
            "train_acc": jnp.sum(metrics["train_acc"] * weights)
            / jnp.maximum(jnp.sum(weights), 1e-12),
            "samples": jnp.sum(weights),
        }
        return agg_vars, new_state, round_metrics

    return aggregate


def _zeros_like(t):
    return jax.tree_util.tree_map(jnp.zeros_like, t)


def _stack_zeros_like(t, n: int):
    return jax.tree_util.tree_map(
        lambda x: jnp.zeros((n,) + x.shape, x.dtype), t)


class ParrotAPI:
    def __init__(self, args: Any, device: Any, dataset: Tuple, bundle: Any,
                 use_mesh: bool = False) -> None:
        self.args = args
        self.bundle = bundle
        self.algo = str(getattr(args, "federated_optimizer", "FedAvg"))
        self.use_mesh = use_mesh
        (self.train_num, self.test_num, self.train_global, self.test_global,
         self.local_num_dict, self.train_data_local_dict,
         self.test_data_local_dict, self.class_num) = dataset

        self.n_total = int(args.client_num_in_total)
        self.k = int(args.client_num_per_round)
        bs = int(getattr(args, "batch_size", 32))
        self.bs = bs
        max_n = max(self.local_num_dict.values())
        self.nb = max(1, -(-int(max_n) // bs))
        #: hetero size-bucketing (reference `core/schedule` capability on the
        #: vmapped hot path): >1 splits clients into size strata so per-round
        #: compute tracks the size DISTRIBUTION, not the max client
        self.n_buckets = max(1, int(getattr(args, "hetero_buckets", 1) or 1))

        # ---- device-resident dataset + per-client index matrix ------------
        x_all, y_all = self.train_global
        # data_dtype: bfloat16 halves the resident footprint AND the gather
        # bandwidth for image data (models cast to their compute dtype
        # anyway); default keeps the bundle's input dtype
        store_dtype = bundle.input_dtype
        if str(getattr(args, "data_dtype", "") or "") == "bfloat16" \
                and bundle.input_dtype == jnp.float32:
            store_dtype = jnp.bfloat16
        self.x_all = jnp.asarray(np.asarray(x_all), store_dtype)
        self.y_all = jnp.asarray(np.asarray(y_all))
        cap = self.nb * bs
        idx_mat = np.full((self.n_total, cap), -1, np.int32)
        # map each client's global sample indices into its padded slots
        self._client_rows = {}
        for cid in range(self.n_total):
            xi, yi = self.train_data_local_dict[cid]
            n_i = min(len(yi), cap)
            rows = self._find_rows(cid, n_i)
            idx_mat[cid, :n_i] = rows
        self.idx_mat = jnp.asarray(idx_mat)
        self.n_samples = jnp.asarray(
            [float(self.local_num_dict[c]) for c in range(self.n_total)],
            jnp.float32)

        # ---- model / engine ------------------------------------------------
        rng = jax.random.PRNGKey(int(getattr(args, "random_seed", 0) or 0))
        self.global_vars = bundle.init_variables(rng, batch_size=min(bs, 8))
        self.local_update = build_local_update(bundle, args)
        self.eval_step = jax.jit(build_eval_step(bundle))

        # ---- server state --------------------------------------------------
        self.server_state: Dict[str, Any] = {}
        if self.algo == FED_OPT_FEDOPT:
            # mirror build_aggregate's channel choice: fused epilogue
            # state ({m, v, t} f32 trees) when the server optimizer maps,
            # optax state otherwise (yogi/adagrad/robust rounds)
            fused_opt = (_epilogue.spec_from_args(args)
                         if parse_robust_agg(
                             getattr(args, "robust_agg", None)) is None
                         else None)
            if fused_opt is not None:
                self.server_state["opt_state"] = _epilogue.init_opt_state(
                    self.global_vars["params"], fused_opt)
            else:
                self.server_tx = build_server_optimizer(args)
                self.server_state["opt_state"] = self.server_tx.init(
                    self.global_vars["params"])
        if self.algo == FED_OPT_SCAFFOLD:
            self.server_state["c_global"] = _zeros_like(
                self.global_vars["params"])
            self.server_state["c_locals"] = _stack_zeros_like(
                self.global_vars["params"], self.n_total)
        if self.algo == FED_OPT_FEDDYN:
            self.server_state["h"] = _zeros_like(self.global_vars["params"])
            self.server_state["lambdas"] = _stack_zeros_like(
                self.global_vars["params"], self.n_total)
        if self.algo == FED_OPT_MIME:
            self.server_state["momentum"] = _zeros_like(
                self.global_vars["params"])

        # ---- mesh ----------------------------------------------------------
        self.mesh = None
        if use_mesh:
            dcn = dict(getattr(args, "dcn_mesh_shape", None) or {})
            dcn_prod = int(np.prod(list(dcn.values()))) if dcn else 1
            shape = getattr(args, "mesh_shape", None) or {
                AXIS_CLIENTS: max(
                    min(len(jax.devices()) // dcn_prod, self.k), 1)}
            self.mesh = (build_hybrid_mesh(shape, dcn) if dcn
                         else build_mesh(shape))

        self._build_buckets()
        # the dataset/index arrays ride as EXPLICIT jit arguments — if the
        # round step closed over them they would be lowered as embedded HLO
        # constants (hundreds of MB at 50k-sample scale), which bloats the
        # program beyond what remote-compile services accept
        self.device_data = {"x": self.x_all, "y": self.y_all,
                            "idx": self.idx_mat, "w": self.n_samples}
        if self.buckets is not None:
            self.device_data["bidx"] = [b["idx"] for b in self.buckets]
            self.device_data["bgids"] = [b["gids"] for b in self.buckets]
            if any(b["nb"] < b["nb_full"] for b in self.buckets):
                # capped buckets rotate per-round sample windows, which
                # needs each member's true size inside the jit
                self.device_data["bsizes"] = [b["sizes"]
                                              for b in self.buckets]
        self.round_step = jax.jit(self._build_round_step(),
                                  donate_argnums=(1, 2))
        if self.n_buckets > 1:
            self.bucketed_round_step = jax.jit(
                self._build_bucketed_round_step(), donate_argnums=(1, 2))
        self.multi_round_step = None  # built lazily for the scan fast path
        #: True when the fused executable was deserialized from the AOT
        #: cache instead of compiled — the committed cross-process proof
        #: (tests/test_aot_cache.py) and bench.py's warm/cold marker
        self.aot_cache_hit = False
        self._fused_is_plain_jit = False
        #: XLA cost/memory analysis of the fused program, captured by the
        #: flight recorder at AOT time (None until built, or when the
        #: backend reports nothing) — bench.py's measured-MFU source
        self.program_costs: Optional[Dict[str, Any]] = None
        self.metrics_history: List[Dict[str, Any]] = []
        #: warm pool (compile-ahead): {tag: {hit, seconds}} per executable
        #: precompiled/cache-loaded in the background; empty until started
        self._compile_ahead_thread: Optional[threading.Thread] = None
        #: guards compile_ahead_report and the start-once check-then-act:
        #: the warm-pool worker fills the report while the main thread
        #: reads it (and two concurrent starters must not spawn two pools)
        self._ca_lock = named_lock("ParrotAPI._ca_lock")
        self.compile_ahead_report: Dict[str, Any] = {}
        #: resize warm pool: {mesh axis size: compiled step} precompiled
        #: for the ±1-step slot ladder (half/double of the current gang)
        #: so an announced re-mesh installs a ready executable instead of
        #: paying a fresh compile inside the downtime window
        self._resize_warm: Dict[int, Any] = {}
        self._resize_warm_thread: Optional[threading.Thread] = None
        #: last resize announce this process acked — a fast next boundary
        #: must not re-latch the same request before the scheduler
        #: collects the ack and clears the file
        self._resize_acked: Optional[Dict[str, Any]] = None
        if self.compile_ahead_enabled():
            self.start_compile_ahead()
        if flight_recorder.enabled():
            # the uploads above are async; force + time them so the h2d
            # bucket carries the real dataset-transfer cost, and count
            # the resident bytes at the boundary
            with flight_recorder.phase("h2d", program="parrot/device_data"):
                jax.block_until_ready(self.device_data)
            flight_recorder.note_transfer(
                "h2d", flight_recorder.tree_nbytes(self.device_data))

    def _build_buckets(self) -> None:
        """Split clients into size strata (equal client counts, stratum
        count snapped to a divisor of k) with per-stratum batch capacity
        nb_b = ceil(max_size_in_stratum / bs).  Per round each stratum
        contributes exactly k/B clients (proportionate stratified sampling
        — every client's inclusion probability is exactly k/N), so the
        padded compute is Σ_b (k/B)·nb_b·bs ≈ k·mean_size instead of
        k·max_size.

        This is the reference heterogeneity-aware scheduler capability
        (`core/schedule/seq_train_scheduler.py`, SURVEY §2.4 fedavg_seq)
        re-expressed for the vmapped hot path: strata ARE the schedule,
        chosen once from the static partition."""
        #: 0 = off (full local epochs, pad to the stratum max); >0 caps
        #: each stratum's batch capacity at cap·mean_size with per-round
        #: rotating sample windows for over-cap clients (PERF003's fix:
        #: padded compute tracks the size DISTRIBUTION's mean, not max)
        self.bucket_cap = float(
            getattr(self.args, "hetero_bucket_cap", 0.0) or 0.0)
        if self.n_buckets <= 1:
            self.buckets = None
            return
        # snap the stratum count to a DIVISOR of k (closest to the request,
        # larger on ties): equal-count strata with equal integer quotas
        # q = k/B make every client's inclusion probability exactly
        # q/(N/B) = k/N — fixed unequal quotas would permanently
        # over-sample one size class.  Residual bias only when B ∤ N
        # (array_split sizes differ by 1 → |Δp| ≤ k/(N·(N/B−1))).
        sizes = np.asarray([self.local_num_dict[c]
                            for c in range(self.n_total)])
        plan = bucket_plan(sizes, self.k, self.bs, self.n_buckets,
                           self.bucket_cap)
        if len(plan) <= 1:
            self.buckets = None
            self.n_buckets = 1
            return
        self.n_buckets = len(plan)
        idx_mat = np.asarray(self.idx_mat)
        self.buckets = []
        for b in plan:
            g = b["members"]
            # the index matrix keeps FULL capacity (largest member) so a
            # capped bucket's rotating window can address every sample;
            # the compute capacity nb may be smaller
            self.buckets.append({
                "gids": jnp.asarray(g.astype(np.int32)),
                "idx": jnp.asarray(idx_mat[g, :b["nb_full"] * self.bs]),
                "sizes": jnp.asarray(sizes[g].astype(np.int32)),
                "nb": b["nb"],
                "nb_full": b["nb_full"],
                "k": b["q"],
                "padded": b["padded"],
                "real": b["real"],
            })

    def bucket_waste_stats(self) -> Optional[Dict[str, Any]]:
        """Per-bucket padded-vs-real accounting for the bench JSON and the
        PERF003 padding-waste lint (None on the uniform path)."""
        if self.buckets is None:
            return None
        return {
            "bs": self.bs,
            "cap_ratio": self.bucket_cap,
            "buckets": [{"q": b["k"], "nb": b["nb"],
                         "nb_full": b["nb_full"], "padded": b["padded"],
                         "real": round(float(b["real"]), 1)}
                        for b in self.buckets],
            "padded_samples_per_round": int(
                sum(b["padded"] for b in self.buckets)),
            "expected_real_per_round": round(
                float(sum(b["real"] for b in self.buckets)), 1),
        }

    def _find_rows(self, cid: int, n_i: int) -> np.ndarray:
        """Global row indices of client cid's samples (the partition index
        map stashed by data_loader.load; recomputed identically if absent)."""
        rows_map = getattr(self.args, "client_row_map", None)
        if rows_map is None:
            from ...data.partition import partition
            y = np.asarray(self.train_global[1])
            labels = y if y.ndim == 1 else y[:, 0]
            m = partition(labels, self.n_total,
                          str(getattr(self.args, "partition_method", "hetero")),
                          float(getattr(self.args, "partition_alpha", 0.5) or 0.5),
                          int(getattr(self.args, "random_seed", 0) or 0))
            rows_map = {c: np.asarray(m[c], np.int64) for c in m}
            setattr(self.args, "client_row_map", rows_map)
        return rows_map[cid][:n_i]

    def _gather_batches(self, data, client_ids, idx_mat, nb_b):
        """Device-resident gather: padded per-client slots → [K, nb_b, bs]
        batch grids with validity masks (shared by the uniform and
        bucketed round steps).  ``data`` carries the traced dataset arrays
        (explicit jit args, never closure constants)."""
        idx = idx_mat[client_ids]                           # [K, cap]
        return self._grid_from_idx(data, idx, nb_b)

    def _gather_batches_windowed(self, data, client_rows, idx_mat, sizes,
                                 nb_b, key):
        """Rotating-window gather for capped buckets: a client larger than
        the bucket's compute capacity contributes a per-round circular
        window of ``nb_b·bs`` of its samples (uniform random start)
        instead of a full epoch — padded compute tracks the stratum mean
        while every sample is still visited across rounds.  Shapes stay
        static: the window is a mod-n_i position gather."""
        capn = nb_b * self.bs
        rows = idx_mat[client_rows]                        # [K, full_cap]
        n_i = jnp.maximum(sizes[client_rows], 1)[:, None]  # [K, 1]
        j = jnp.arange(capn, dtype=jnp.int32)[None, :]
        start = jax.random.randint(
            key, (rows.shape[0], 1), 0, jnp.int32(1 << 30),
            dtype=jnp.int32) % n_i
        # over-cap clients read a circular window; everyone else reads
        # their padded slots verbatim (idx -1 padding masks the tail)
        pos = jnp.where(n_i > capn, (start + j) % n_i, j)
        idx = jnp.take_along_axis(rows, pos, axis=1)       # [K, capn]
        return self._grid_from_idx(data, idx, nb_b)

    def _grid_from_idx(self, data, idx, nb_b):
        bs = self.bs
        safe = jnp.maximum(idx, 0)
        x = data["x"][safe]                                 # [K, cap, ...]
        y = data["y"][safe]
        mask = (idx >= 0).astype(jnp.float32)
        return {"x": x.reshape((x.shape[0], nb_b, bs) + x.shape[2:]),
                "y": y.reshape((y.shape[0], nb_b, bs) + y.shape[2:]),
                "mask": mask.reshape((mask.shape[0], nb_b, bs))}

    # ------------------------------------------------------------------
    def _grid_sharding(self, k_b: int, mesh: Any = None
                       ) -> Optional[NamedSharding]:
        return grid_sharding(mesh if mesh is not None else self.mesh,
                             k_b, self.bs)

    def _build_round_step(self, mesh: Any = None):
        # the client axis shards over EVERY mesh axis (clients is parrot's
        # only parallel dimension, so a DCN axis extends it across slices
        # rather than replicating the round); a quota smaller than the
        # mesh shards the intra-batch axis instead (see _grid_sharding).
        # ``mesh`` overrides self.mesh so the resize warm pool can build
        # steps for candidate slot counts without touching the live mesh
        clients_sharding = self._grid_sharding(self.k, mesh=mesh)

        per_client_algo_state = self._per_client_algo_state
        in_axes_algo = self._in_axes_algo()
        aggregate = self._build_aggregate()

        def round_step(data, global_vars, server_state, client_ids, rng):
            batches = self._gather_batches(data, client_ids, data["idx"],
                                           self.nb)
            if clients_sharding is not None:
                batches = jax.lax.with_sharding_constraint(
                    batches, clients_sharding)
            rngs = jax.random.split(rng, client_ids.shape[0])
            algo_state = per_client_algo_state(server_state, client_ids)
            new_vars, algo_out, metrics = jax.vmap(
                self.local_update,
                in_axes=(None, 0, 0, in_axes_algo))(
                    global_vars, batches, rngs, algo_state or None)
            weights = data["w"][client_ids]
            return aggregate(global_vars, server_state, client_ids,
                             new_vars, algo_out, metrics, weights)

        return round_step

    def _per_client_algo_state(self, server_state, client_ids):
        return per_client_algo_state(self.algo, server_state, client_ids)

    def _in_axes_algo(self):
        return algo_in_axes(self.algo)

    def _build_aggregate(self):
        return build_aggregate(self.args, self.algo, self.n_total,
                               server_tx=getattr(self, "server_tx", None))

    def _build_bucketed_round_step(self, mesh: Any = None):
        """One round over size strata: each bucket vmaps its own quota of
        clients at its own batch capacity (one compile total — the python
        loop over buckets unrolls into one jit graph), then all buckets'
        stacked outputs concatenate into the shared aggregation.  Client
        sampling is proportionate-stratified ON DEVICE (inclusion
        probability k/N per client; deviation from the reference's host
        `np.random.seed(round)` draws is documented in run_rounds_fused)."""
        per_client_algo_state = self._per_client_algo_state
        in_axes_algo = self._in_axes_algo()
        aggregate = self._build_aggregate()
        buckets = self.buckets
        # per-bucket sharding chosen from the bucket's own quota (mesh
        # path: the round-2 bucketed step never sharded — VERDICT weak #1)
        bucket_shardings = [self._grid_sharding(b["k"], mesh=mesh)
                            for b in buckets]

        # capped buckets draw a third key for the rotating window; the
        # uncapped layout keeps the historical 2-key stream so existing
        # configs trace (and AOT-cache) identically
        any_capped = any(b["nb"] < b["nb_full"] for b in buckets)
        keys_per_bucket = 3 if any_capped else 2

        def round_step(data, global_vars, server_state, rng):
            outs = []
            keys = jax.random.split(rng, keys_per_bucket * len(buckets))
            for i, b in enumerate(buckets):
                rows = jax.random.permutation(
                    keys[keys_per_bucket * i], b["gids"].shape[0])[:b["k"]]
                gids = data["bgids"][i][rows]
                if b["nb"] < b["nb_full"]:
                    batches = self._gather_batches_windowed(
                        data, rows, data["bidx"][i], data["bsizes"][i],
                        b["nb"], keys[keys_per_bucket * i + 2])
                else:
                    batches = self._gather_batches(data, rows,
                                                   data["bidx"][i], b["nb"])
                if bucket_shardings[i] is not None:
                    batches = jax.lax.with_sharding_constraint(
                        batches, bucket_shardings[i])
                rngs = jax.random.split(keys[keys_per_bucket * i + 1],
                                        b["k"])
                algo_state = per_client_algo_state(server_state, gids)
                new_vars, algo_out, metrics = jax.vmap(
                    self.local_update,
                    in_axes=(None, 0, 0, in_axes_algo))(
                        global_vars, batches, rngs, algo_state or None)
                outs.append((new_vars, algo_out, metrics,
                             data["w"][gids], gids))

            def cat(trees):
                return jax.tree_util.tree_map(
                    lambda *xs: jnp.concatenate(xs, axis=0), *trees)

            new_vars = cat([o[0] for o in outs])
            algo_out = cat([o[1] for o in outs])
            metrics = cat([o[2] for o in outs])
            weights = jnp.concatenate([o[3] for o in outs])
            client_ids = jnp.concatenate([o[4] for o in outs])
            return aggregate(global_vars, server_state, client_ids,
                             new_vars, algo_out, metrics, weights)

        return round_step

    # ------------------------------------------------------------------
    def _build_multi_round_step(self):
        """Scan-rounds fast path: up to FUSED_CHUNK_ROUNDS rounds inside
        ONE jit dispatch.

        Amortizes per-call dispatch/transfer overhead (dominant when client
        models are small or the device is remote).  Client sampling moves
        on-device (`jax.random.permutation`), which deliberately diverges
        from the reference's host `np.random.seed(round)` stream — same
        distribution, different draws; the default per-round path keeps
        reference parity.

        The scan length is ALWAYS the full chunk; a traced ``n_active``
        scalar masks the tail via per-round `lax.cond` (idle rounds pass
        the carry through at ~zero cost).  One compiled program therefore
        serves EVERY round count — which is what makes the AOT export
        cache (`_ensure_multi_round_step`) a single artifact instead of
        one per remainder shape."""
        k = self.k
        n_total = self.n_total
        chunk = self.FUSED_CHUNK_ROUNDS
        #: stable metrics contract of `_build_aggregate`
        idle_rm = {"train_loss": jnp.zeros((), jnp.float32),
                   "train_acc": jnp.zeros((), jnp.float32),
                   "samples": jnp.zeros((), jnp.float32)}
        if self.n_buckets > 1:
            bucketed = self._build_bucketed_round_step()

            def make_body(data, n_active):
                def body(carry, r):
                    gv, st, rng = carry
                    rng, k2 = jax.random.split(rng)
                    gv, st, rm = jax.lax.cond(
                        r < n_active,
                        lambda op: bucketed(data, op[0], op[1], k2),
                        lambda op: (op[0], op[1], dict(idle_rm)),
                        (gv, st))
                    return (gv, st, rng), rm
                return body
        else:
            round_step = self._build_round_step()

            def make_body(data, n_active):
                def body(carry, r):
                    gv, st, rng = carry
                    rng, k1, k2 = jax.random.split(rng, 3)

                    def run(op):
                        ids = jax.random.permutation(k1, n_total)[:k]
                        return round_step(data, op[0], op[1], ids, k2)

                    gv, st, rm = jax.lax.cond(
                        r < n_active, run,
                        lambda op: (op[0], op[1], dict(idle_rm)), (gv, st))
                    return (gv, st, rng), rm
                return body

        def multi(data, global_vars, server_state, rng, n_active):
            (gv, st, _), rms = jax.lax.scan(
                make_body(data, n_active),
                (global_vars, server_state, rng), jnp.arange(chunk))
            return gv, st, rms

        return jax.jit(multi, donate_argnums=(1, 2))

    # ------------------------------------------------------------------
    def _aot_cache_path(self, tag: str = "mrs") -> Optional[str]:
        """Disk path for a serialized parrot executable, or None when
        AOT caching is off.  ``tag`` names the program — ``mrs`` (fused
        multi-round scan), ``rs`` (uniform round step), ``brs`` (bucketed
        round step; one program embedding every bucket signature from
        ``bucket_plan()``) — and the key digests everything the traced
        program depends on: config knobs, data/model shapes, bucket
        layout, device topology, jax version, AND the source files that
        build the trace — so a stale artifact can never be replayed."""
        if not bool(getattr(self.args, "parrot_aot_cache", True)):
            return None
        import hashlib
        import os

        # FEDML_TPU_AOT_CACHE_DIR is the pod scheduler's compile-sharing
        # contract: every job dispatched on the pod points here, so one
        # tenant's parrot compile is a digest-keyed cache hit for the
        # next job with the same executable shape.  Explicit config wins.
        base = (getattr(self.args, "aot_cache_dir", None)
                or os.environ.get("FEDML_TPU_AOT_CACHE_DIR")
                or jax.config.jax_compilation_cache_dir)
        if not base:
            return None

        h = hashlib.sha256()
        h.update(jax.__version__.encode())
        devs = jax.devices()
        h.update(f"{devs[0].platform}:{devs[0].device_kind}:"
                 f"{len(devs)}".encode())
        if self.mesh is not None:
            h.update(repr(tuple(zip(self.mesh.axis_names,
                                    self.mesh.devices.shape))).encode())
        cfg = [str(getattr(self.args, f, None)) for f in (
            "model", "dataset", "federated_optimizer", "client_optimizer",
            "learning_rate", "momentum", "weight_decay", "epochs",
            "batch_size", "client_num_in_total", "client_num_per_round",
            "compute_dtype", "data_dtype", "hetero_buckets", "conv_impl",
            "server_lr", "server_momentum", "feddyn_alpha", "fedprox_mu",
            "random_seed", "robust_agg", "hetero_bucket_cap",
            "fused_epilogue", "server_optimizer")]
        h.update("|".join(cfg).encode())
        h.update(repr((self.x_all.shape, str(self.x_all.dtype),
                       self.y_all.shape, self.nb, self.bs,
                       self.FUSED_CHUNK_ROUNDS)).encode())
        if self.buckets is not None:
            h.update(repr([(b["k"], b["nb"], b["nb_full"])
                           for b in self.buckets]).encode())
        pkg = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        for rel in ("simulation/parrot/parrot_api.py",
                    "ml/engine/local_update.py",
                    "ml/engine/model_bundle.py",
                    "ml/engine/optimizers.py",
                    "ml/aggregator/agg_operator.py",
                    "ml/aggregator/robust.py",
                    "ops/epilogue.py",
                    "ops/pallas_ops.py"):
            try:
                with open(os.path.join(pkg, rel), "rb") as f:
                    h.update(f.read())
            except OSError:
                h.update(rel.encode())
        try:
            for mod in sorted(os.listdir(os.path.join(pkg, "models"))):
                if mod.endswith(".py"):
                    with open(os.path.join(pkg, "models", mod), "rb") as f:
                        h.update(f.read())
            # the artifact is a pickle, so the cache dir must be a private
            # trust domain: create 0o700, refuse dirs owned by another
            # uid, and strip group/other permissions from pre-existing
            # dirs (makedirs only applies the mode on creation) — an
            # attacker able to write here gets code execution in the
            # training process
            os.makedirs(base, mode=0o700, exist_ok=True)
            if hasattr(os, "getuid"):
                st = os.stat(base)
                if st.st_uid != os.getuid():
                    logging.warning(
                        "parrot: AOT cache dir %s owned by uid %d (not "
                        "ours); caching off", base, st.st_uid)
                    return None
                if st.st_mode & 0o077:
                    os.chmod(base, 0o700)
                    if os.stat(base).st_mode & 0o022:
                        logging.warning(
                            "parrot: AOT cache dir %s stays group/world "
                            "writable; caching off", base)
                        return None
        except OSError as e:  # unwritable cache dir degrades, never aborts
            logging.warning("parrot: AOT cache dir unusable (%s); caching "
                            "off", e)
            return None
        return os.path.join(base,
                            f"parrot_{tag}_{h.hexdigest()[:24]}.jaxexp")

    def _ensure_multi_round_step(self) -> None:
        """Build (or load) the fused program, attributing the wall time
        to the flight recorder's ``compile`` bucket and capturing the
        program's XLA cost/memory analysis (``self.program_costs``) for
        measured MFU."""
        if self.multi_round_step is not None:
            return
        t = self._compile_ahead_thread
        if t is not None and t.is_alive():
            # warm pool is already building it — join instead of racing
            t.join()
        if self.multi_round_step is not None:
            return
        with flight_recorder.phase("compile",
                                   program="parrot/fused_round_scan"):
            self._build_or_load_multi_round_step()
        if self.program_costs is None:
            # works for a freshly-compiled AND a cache-loaded executable;
            # stays None on the plain-jit fallback (nothing compiled yet)
            self.program_costs = flight_recorder.note_program(
                "parrot/fused_round_scan", self.multi_round_step,
                chunk_rounds=self.FUSED_CHUNK_ROUNDS)

    def _build_or_load_multi_round_step(self) -> None:
        """With a cache dir
        configured, the COMPILED EXECUTABLE round-trips through
        `jax.experimental.serialize_executable`: a warm process skips the
        ~40 s retrace, ~5-20 s lowering AND the XLA compile entirely
        (~29 s executable upload through the tunnel; 94 s → 29 s warm
        start, VERDICT r3 item 3).  `jax.export` was tried first and
        REJECTED: its deserialized StableHLO recompiles into a program
        that executes the chunk 2.4x slower than the jit path (44.8 s vs
        18.9 s measured on the north star — BENCH_NOTES round 4); the
        serialized executable is bit-identical to what jit ran.

        The artifact is a pickle (executable bytes + arg trees) keyed by
        `_aot_cache_path`'s config+code digest, loaded only from the
        local cache dir this process also writes — same trust domain as
        jax's own persistent compilation cache."""
        if self.multi_round_step is not None:
            return

        fn = self._build_multi_round_step()
        path = self._aot_cache_path()
        loaded = self._load_executable(path)
        if loaded is not None:
            self.multi_round_step = loaded
            self.aot_cache_hit = True
            logging.info("parrot: fused executable loaded from "
                         "AOT cache %s", path)
            return
        # compile EAGERLY even without a cache dir: readiness then always
        # includes the compile, so callers timing "program ready" vs
        # "first chunk" (bench.py) measure the same thing on every path
        try:
            spec = self._aot_arg_spec(
                (self.device_data, self.global_vars,
                 self.server_state, jax.random.PRNGKey(0),
                 jnp.zeros((), jnp.int32)))
            compiled = fn.trace(*spec).lower().compile()
        except Exception as e:
            logging.warning("parrot: AOT compile failed (%s); using plain "
                            "jit", e)
            self.multi_round_step = fn
            self._fused_is_plain_jit = True
            return
        self.multi_round_step = compiled
        self._save_executable(path, compiled)

    @staticmethod
    def _aot_arg_spec(args_tree):
        """ShapeDtypeStructs for ``trace()`` that carry the committed
        arrays' shardings — specs from shape/dtype alone can compile a
        program that reshards (or fails) at call time on a multi-chip
        mesh."""

        def _spec(a):
            sh = getattr(a, "sharding", None)
            if sh is not None:
                try:
                    return jax.ShapeDtypeStruct(a.shape, a.dtype,
                                                sharding=sh)
                except TypeError:
                    pass
            return jax.ShapeDtypeStruct(a.shape, a.dtype)

        return jax.tree_util.tree_map(_spec, args_tree)

    def _load_executable(self, path: Optional[str]):
        """Deserialize a cached executable, or None (missing/stale/
        corrupt/foreign-owned — load failures degrade to a recompile,
        never abort)."""
        import os
        import pickle

        if not path or not os.path.exists(path):
            return None
        try:
            from jax.experimental import serialize_executable

            with open(path, "rb") as f:
                # fstat the OPEN fd (not the path) so a symlink swap
                # between check and read can't redirect the unpickle
                if hasattr(os, "getuid"):
                    import stat as _stat

                    st = os.fstat(f.fileno())
                    if (st.st_uid != os.getuid()
                            or not _stat.S_ISREG(st.st_mode)):
                        raise PermissionError(
                            f"{path} not a regular file owned by us; "
                            "refusing to unpickle")
                blob = pickle.load(f)
            return serialize_executable.deserialize_and_load(*blob)
        except Exception as e:  # stale/corrupt → rebuild
            logging.warning("parrot: AOT cache load failed (%s); "
                            "recompiling", e)
            return None

    def _save_executable(self, path: Optional[str], compiled) -> None:
        """Serialize ``compiled`` to the shared cache (atomic replace);
        persistence failures must not discard the live executable."""
        import os
        import pickle

        if not path:
            return
        try:
            from jax.experimental import serialize_executable

            blob = serialize_executable.serialize(compiled)
            tmp = path + ".tmp"
            with open(tmp, "wb") as f:
                pickle.dump(blob, f)
            os.replace(tmp, path)
            logging.info("parrot: executable cached to %s", path)
        except Exception as e:
            logging.warning("parrot: AOT cache write failed (%s); "
                            "executable kept in-memory only", e)

    # ---- per-bucket AOT compile-ahead (warm pool) ---------------------

    def compile_ahead_enabled(self) -> bool:
        import os

        return bool(getattr(self.args, "parrot_compile_ahead", False)
                    or os.environ.get("FEDML_TPU_COMPILE_AHEAD"))

    def start_compile_ahead(self, wait: bool = False) -> Dict[str, Any]:
        """Background warm pool: precompile (or cache-load) every round
        executable this config can dispatch — the per-round step (``rs``,
        or ``brs``: ONE program embedding every bucket signature from
        ``bucket_plan()``) and the fused multi-round scan (``mrs``) —
        keyed by the ``_aot_cache_path`` digests and shared through
        ``FEDML_TPU_AOT_CACHE_DIR``.  Round 1 then stops paying compile
        in the flight log: the wall time lands in the standalone
        ``compile_ahead`` phase (concurrent with host setup) instead of
        the first round's ``compile`` bucket, and a second process with
        the same digest loads the serialized executables outright.

        Returns ``compile_ahead_report`` — ``{tag: {hit, seconds}}``,
        fully populated once the worker finishes (``wait=True`` blocks)."""
        with self._ca_lock:
            # start-once under the lock: two concurrent starters (e.g. an
            # eager __init__ and an explicit warm-up call) must not spawn
            # two pools compiling the same executables
            t = self._compile_ahead_thread
            if t is None:
                t = threading.Thread(target=self._compile_ahead_worker,
                                     name="parrot-compile-ahead",
                                     daemon=True)
                self._compile_ahead_thread = t
                t.start()
        if wait:
            t.join()
        with self._ca_lock:
            # snapshot: the worker may still be appending to the live dict
            return dict(self.compile_ahead_report)

    def _note_compile_ahead(self, tag: str, entry: Any) -> None:
        with self._ca_lock:
            self.compile_ahead_report[tag] = entry

    def join_compile_ahead(self, timeout: Optional[float] = None) -> None:
        """Wait out the warm pool (no-op when never started).  Called on
        every train() exit path so the compile thread cannot outlive the
        run — a daemon thread killed at interpreter exit can die mid
        AOT-cache write and leave a torn cache entry for the next
        process to load."""
        t = self._compile_ahead_thread
        if t is None or not t.is_alive():
            return
        t.join(timeout=timeout)
        if t.is_alive():
            logging.warning(
                "parrot: compile-ahead worker still running after %ss — "
                "continuing without it", timeout)

    def _compile_ahead_worker(self) -> None:
        try:
            tag = "brs" if self.n_buckets > 1 else "rs"
            self._note_compile_ahead(tag, self._warm_step(tag))
            t0 = time.perf_counter()
            with flight_recorder.phase("compile_ahead",
                                       program="parrot/fused_round_scan"):
                self._build_or_load_multi_round_step()
            self._note_compile_ahead(
                "mrs", {"hit": bool(self.aot_cache_hit),
                        "seconds": round(time.perf_counter() - t0, 3)})
            if self.program_costs is None and not self._fused_is_plain_jit:
                self.program_costs = flight_recorder.note_program(
                    "parrot/fused_round_scan", self.multi_round_step,
                    chunk_rounds=self.FUSED_CHUNK_ROUNDS)
        except Exception as e:  # warm pool must never take the run down
            self._note_compile_ahead("error", str(e))
            logging.warning("parrot: compile-ahead worker failed (%s)", e)

    def _warm_step(self, tag: str) -> Dict[str, Any]:
        """Precompile (or cache-load) one per-round step executable and
        install it in place of the plain jit, wrapped with a bind-failure
        fallback."""
        t0 = time.perf_counter()
        if tag == "brs":
            jit_fn = self.bucketed_round_step
            spec = self._aot_arg_spec(
                (self.device_data, self.global_vars, self.server_state,
                 jax.random.PRNGKey(0)))
        else:
            jit_fn = self.round_step
            spec = self._aot_arg_spec(
                (self.device_data, self.global_vars, self.server_state,
                 jnp.zeros((self.k,), jnp.int32), jax.random.PRNGKey(0)))
        path = self._aot_cache_path(tag)
        compiled = self._load_executable(path)
        hit = compiled is not None
        if compiled is None:
            with flight_recorder.phase(
                    "compile_ahead", program=f"parrot/round_step_{tag}"):
                compiled = jit_fn.trace(*spec).lower().compile()
            self._save_executable(path, compiled)
        wrapped = self._wrap_step_with_fallback(compiled, jit_fn, tag)
        if tag == "brs":
            self.bucketed_round_step = wrapped
        else:
            self.round_step = wrapped
        return {"hit": hit, "seconds": round(time.perf_counter() - t0, 3)}

    def _wrap_step_with_fallback(self, compiled, jit_fn, tag: str):
        """An AOT executable can reject its args at bind time (layout/
        sharding drift vs what jit would infer); bind failures leave the
        donated buffers intact, so fall back to the plain jit once.  An
        execution failure has already consumed the donation — detect
        (deleted leaves) and re-raise."""
        state = {"fn": compiled, "fell_back": False}

        def call(*call_args):
            if state["fell_back"]:
                return jit_fn(*call_args)
            try:
                return state["fn"](*call_args)
            except Exception as e:
                for tree in call_args:
                    for leaf in jax.tree_util.tree_leaves(tree):
                        if (hasattr(leaf, "is_deleted")
                                and leaf.is_deleted()):
                            raise
                logging.warning(
                    "parrot: warm %s executable rejected its args (%s); "
                    "falling back to plain jit", tag, e)
                state["fell_back"] = True
                return jit_fn(*call_args)

        return call

    # ---- elastic resize (pod scheduler contract) ----------------------

    def _resize_file(self) -> Optional[str]:
        return (os.environ.get("FEDML_TPU_RESIZE_FILE")
                or getattr(self.args, "resize_file", None))

    def _mesh_axis_for(self, n_slots: int) -> int:
        """Clients-axis size for a gang of ``n_slots`` devices.  Unlike
        __init__'s default-shape heuristic this does NOT clamp to the
        client quota — an explicit mesh wider than ``k`` is legal (the
        intra-batch axis shards instead), and clamping would turn a
        grow-back to 8 slots into a silent 4-wide mesh."""
        return max(min(int(n_slots), len(jax.devices())), 1)

    def _step_arg_spec(self, tag: str):
        """Shape/dtype-only specs (NO shardings, unlike `_aot_arg_spec`):
        a resize candidate compiles against a mesh the live arrays aren't
        on yet, and a pinned committed sharding would be rejected as an
        incompatible device set.  The uncommitted-arg layout the compiler
        picks here is exactly what the post-remesh call binds with."""
        if tag == "brs":
            tree = (self.device_data, self.global_vars, self.server_state,
                    jax.random.PRNGKey(0))
        else:
            tree = (self.device_data, self.global_vars, self.server_state,
                    jnp.zeros((self.k,), jnp.int32), jax.random.PRNGKey(0))
        return jax.tree_util.tree_map(
            lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), tree)

    def prewarm_resize(self, around: int) -> None:
        """Warm the resize ladder: precompile the per-round step for the
        ±1-step slot counts (half and double of ``around``) in the
        background, so the executable an announced re-mesh will need is
        already sitting in ``_resize_warm`` when the round boundary
        latches it.  Arg shapes don't change with the gang size — only
        the shardings do — so one spec serves every candidate."""
        if not self.use_mesh or self.mesh is None:
            return
        if dict(getattr(self.args, "dcn_mesh_shape", None) or {}):
            return  # hybrid meshes don't resize (see remesh)
        cands = sorted({self._mesh_axis_for(max(int(around) // 2, 1)),
                        self._mesh_axis_for(int(around) * 2)}
                       - {self._mesh_axis_for(int(around))})
        if not cands:
            return
        with self._ca_lock:
            t = self._resize_warm_thread
            if t is not None and t.is_alive():
                return
            t = threading.Thread(target=self._prewarm_resize_worker,
                                 args=(cands,), daemon=True,
                                 name="parrot-resize-warm")
            self._resize_warm_thread = t
            t.start()

    def _compile_resize_candidate(self, axis: int, tag: str) -> None:
        mesh = build_mesh({AXIS_CLIENTS: axis})
        fn = (self._build_bucketed_round_step(mesh=mesh)
              if tag == "brs" else self._build_round_step(mesh=mesh))
        t0 = time.perf_counter()
        with flight_recorder.phase(
                "compile_ahead",
                program=f"parrot/round_step_{tag}_slots{axis}"):
            compiled = (jax.jit(fn, donate_argnums=(1, 2))
                        .trace(*self._step_arg_spec(tag))
                        .lower().compile())
        with self._ca_lock:
            self._resize_warm[axis] = compiled
        self._note_compile_ahead(
            f"{tag}_slots{axis}",
            {"hit": False,
             "seconds": round(time.perf_counter() - t0, 3)})

    def _prewarm_resize_worker(self, axis_sizes: List[int]) -> None:
        tag = "brs" if self.n_buckets > 1 else "rs"
        for axis in axis_sizes:
            with self._ca_lock:
                if axis in self._resize_warm:
                    continue
            try:
                self._compile_resize_candidate(axis, tag)
            except Exception as e:  # noqa: BLE001 — warm pool must never
                # take the run down; a cold resize just compiles inline
                logging.warning(
                    "parrot: resize prewarm for %d slots failed (%s)",
                    axis, e)

    def remesh(self, n_slots: int) -> None:
        """Rebuild the device mesh at ``n_slots`` and re-install the
        round executables — the in-place half of the elastic resize
        contract (docs/SCHEDULER.md "Elastic resize").  State crosses
        through host memory (device_get → device_put), so the restored
        values are bitwise-identical and only the sharding changes.
        Raises on any failure; the caller degrades to the preempt
        ladder."""
        if not self.use_mesh or self.mesh is None:
            return  # mesh-free layout: a gang resize changes nothing
        if dict(getattr(self.args, "dcn_mesh_shape", None) or {}):
            raise RuntimeError(
                "elastic resize over a hybrid (DCN) mesh is not "
                "supported — fall back to preempt/resume")
        axis = self._mesh_axis_for(n_slots)
        gv = jax.device_get(self.global_vars)
        ss = jax.device_get(self.server_state)
        self.mesh = build_mesh({AXIS_CLIENTS: axis})
        self.global_vars = jax.device_put(gv)
        self.server_state = jax.device_put(ss)
        with self._ca_lock:
            warm = self._resize_warm.get(axis)
        tag = "brs" if self.n_buckets > 1 else "rs"
        self.round_step = jax.jit(self._build_round_step(),
                                  donate_argnums=(1, 2))
        if self.n_buckets > 1:
            jit_fn = jax.jit(self._build_bucketed_round_step(),
                             donate_argnums=(1, 2))
            self.bucketed_round_step = (
                self._wrap_step_with_fallback(warm, jit_fn, tag)
                if warm is not None else jit_fn)
        elif warm is not None:
            self.round_step = self._wrap_step_with_fallback(
                warm, self.round_step, tag)
        # the fused scan re-lowers lazily at the new layout; its AOT
        # digest keys on the mesh, so the old artifact stays valid for
        # the old size
        self.multi_round_step = None
        self._fused_is_plain_jit = False

    def _maybe_resize(self, ckpt: Any, round_idx: int) -> None:
        """Round-boundary resize latch (the parrot twin of the cross-silo
        server's `_resize_requested`/`_perform_resize`): checkpoint
        first, re-mesh in place, ack — a failed re-mesh acks ``failed``
        (the scheduler walks the resize → preempt → kill ladder) and
        training continues at the old gang until the drain arrives."""
        path = self._resize_file()
        if not path:
            return
        from ...scheduler.pod.runners import ack_resize, read_resize

        req = read_resize(path)
        if req is None or req == self._resize_acked:
            return
        target = int(req["slots"])
        prev = (int(self.mesh.devices.size)
                if self.mesh is not None else None)
        t0 = time.perf_counter()
        try:
            if ckpt is not None:
                # boundary checkpoint BEFORE touching the mesh: whatever
                # happens next, this round is never lost (force=True —
                # the periodic save may already hold this round)
                ckpt.save(round_idx, {
                    "round_idx": round_idx,
                    "global_vars": self.global_vars,
                    "server_state": self.server_state,
                }, force=True)
            self.remesh(target)
            downtime = round(time.perf_counter() - t0, 6)
            self._resize_acked = req
            ack_resize(path, "ok", target, downtime_s=downtime,
                       round=int(round_idx))
            ledger.event("parrot", "resize", round_idx=int(round_idx),
                         outcome="ok", downtime_s=downtime,
                         **{"from": prev, "to": target})
            logging.info(
                "parrot: re-meshed %s -> %d slots in place at round "
                "boundary %d (%.3fs pause)", prev, target, round_idx,
                downtime)
            self.prewarm_resize(target)  # warm the new ladder neighbours
        except Exception:  # noqa: BLE001 — a failed re-mesh must degrade
            # to the preempt ladder, never take the run down mid-round
            logging.exception(
                "parrot: in-place resize to %d slots failed — acking "
                "failed (scheduler falls back to preempt)", target)
            self._resize_acked = req
            try:
                ack_resize(path, "failed", target, round=int(round_idx))
            except OSError:
                pass
            ledger.event("parrot", "resize", round_idx=int(round_idx),
                         outcome="failed", downtime_s=None,
                         **{"from": prev, "to": target})

    #: rounds per fused call — the scan ALWAYS runs this many iterations
    #: and a traced ``n_active`` masks the tail, so exactly ONE compiled
    #: program (and one AOT-cache artifact) serves every total round
    #: count, remainders included.  Measured on v5e through the
    #: remote-TPU tunnel (~115 ms/dispatch): chunk 8 → 27 rounds/s,
    #: 32 → 38, 64 → 41 on the north-star ResNet-56 config; compile time
    #: stays ~30 s at every chunk size, so take the 64-round plateau.
    FUSED_CHUNK_ROUNDS = 64

    def run_rounds_fused(self, n_rounds: int, rng: Optional[jax.Array] = None):
        """Public fast path: run n_rounds fused in fixed-size scan chunks;
        returns stacked per-round metrics (concatenated across chunks)."""
        self._ensure_multi_round_step()
        if rng is None:
            rng = jax.random.PRNGKey(
                int(getattr(self.args, "random_seed", 0) or 0) + 23)
        chunk = self.FUSED_CHUNK_ROUNDS
        out = []
        remaining = int(n_rounds)
        if remaining <= 0:
            # valid no-op: empty stacked metrics, WITHOUT invoking the
            # jitted step (it donates global_vars/server_state — running it
            # just to learn the metrics shape would delete the live state)
            return {"train_loss": np.zeros((0,), np.float32),
                    "train_acc": np.zeros((0,), np.float32),
                    "samples": np.zeros((0,), np.float32)}
        while remaining > 0:
            step = min(chunk, remaining)
            rng, sub = jax.random.split(rng)
            # the scan always runs the full chunk; n_active masks the tail
            # (idle rounds pass the carry through), so one compiled
            # program serves every round count
            with flight_recorder.record_round(
                    "parrot_fused", rounds=step,
                    program="parrot/fused_round_scan") as fr:
                with fr.phase("device_compute"):
                    try:
                        self.global_vars, self.server_state, rms = \
                            self.multi_round_step(
                                self.device_data, self.global_vars,
                                self.server_state, sub,
                                jnp.asarray(step, jnp.int32))
                    except Exception as e:
                        # an AOT/deserialized executable can still reject its
                        # args at bind time (input layout/sharding mismatch vs
                        # what jit would have inferred); bind-time failures
                        # leave the donated buffers intact, so fall back to
                        # the plain jit fn once.  An EXECUTION-time failure
                        # has already consumed the donated state — detect that
                        # (deleted leaves) and re-raise the root cause instead
                        # of crashing later on dead arrays.
                        if self._fused_is_plain_jit:
                            raise

                        def _live(tree):
                            return all(
                                not (hasattr(leaf, "is_deleted")
                                     and leaf.is_deleted())
                                for leaf in jax.tree_util.tree_leaves(tree))

                        if not (_live(self.global_vars)
                                and _live(self.server_state)):
                            raise
                        logging.warning(
                            "parrot: compiled fused step rejected its "
                            "args (%s); falling back to plain jit", e)
                        if self.aot_cache_hit:
                            # the artifact produced a bind-incompatible
                            # executable; drop it so later processes
                            # recompile+rewrite instead of paying
                            # load→bind-fail→retrace forever
                            import os

                            stale = self._aot_cache_path()
                            if stale:
                                try:
                                    os.remove(stale)
                                except OSError:
                                    pass
                        self.multi_round_step = self._build_multi_round_step()
                        self._fused_is_plain_jit = True
                        self.aot_cache_hit = False
                        self.global_vars, self.server_state, rms = \
                            self.multi_round_step(
                                self.device_data, self.global_vars,
                                self.server_state, sub,
                                jnp.asarray(step, jnp.int32))
                    if flight_recorder.enabled():
                        # device-completion sync point: without it the
                        # phase measures dispatch, not execution
                        rms = jax.block_until_ready(rms)
                flops = (self.program_costs or {}).get("flops")
                dev_s = fr.phase_seconds("device_compute")
                if flops and dev_s > 0:
                    # idle masked tail rounds are ~free — charge only the
                    # active fraction of the chunk's analytic FLOPs
                    fr.note(mfu=flight_recorder.measured_mfu(
                        "parrot/fused_round_scan",
                        flops * (step / chunk), dev_s))
            if step < chunk:
                rms = jax.tree_util.tree_map(lambda a: a[:step], rms)
            out.append(rms)
            remaining -= step
        if len(out) == 1:
            return out[0]
        # host-side concat: per-round metrics are tiny, and a device-side
        # jnp.concatenate would pay a fresh XLA compile per chunk count
        return jax.tree_util.tree_map(
            lambda *xs: np.concatenate([np.asarray(x) for x in xs]), *out)

    def _client_sampling(self, round_idx: int) -> np.ndarray:
        if self.n_total == self.k:
            return np.arange(self.k, dtype=np.int32)
        np.random.seed(round_idx)  # reference parity (fedavg_api.py:127-136)
        return np.random.choice(self.n_total, self.k,
                                replace=False).astype(np.int32)

    def train(self) -> Dict[str, Any]:
        try:
            if getattr(self.args, "fused_rounds", False):
                return self._train_fused()
            return self._train_rounds()
        finally:
            self.join_compile_ahead(timeout=60.0)

    def _train_rounds(self) -> Dict[str, Any]:
        comm_rounds = int(self.args.comm_round)
        rng = jax.random.PRNGKey(
            int(getattr(self.args, "random_seed", 0) or 0) + 17)
        test_batches = self._make_test_batches()
        final_metrics: Dict[str, Any] = {}

        # round-level checkpoint/resume (new capability vs reference)
        ckpt = None
        start_round = 0
        ckpt_dir = getattr(self.args, "checkpoint_dir", None)
        ckpt_freq = int(getattr(self.args, "checkpoint_frequency", 10) or 10)
        if ckpt_dir:
            from ...utils.checkpoint import RoundCheckpointer

            ckpt = RoundCheckpointer(str(ckpt_dir))
            state = ckpt.restore()
            if state is not None:
                start_round = int(np.asarray(state["round_idx"])) + 1
                self.global_vars = state["global_vars"]
                if state.get("server_state"):
                    self.server_state = state["server_state"]
                logging.info("resumed from round %d", start_round - 1)

        if self._resize_file() and self.compile_ahead_enabled() \
                and self.mesh is not None:
            # elastic job under the pod scheduler: warm the ±1-step slot
            # ladder now so an announced re-mesh finds its executable hot
            self.prewarm_resize(int(self.mesh.devices.size))
        for round_idx in range(start_round, comm_rounds):
            # the mesh context re-enters per round (not once around the
            # loop) because a round-boundary resize swaps self.mesh
            ctx = (self.mesh if self.mesh is not None
                   else contextlib.nullcontext())
            with ctx:
                t0 = time.time()
                rng, sub = jax.random.split(rng)
                with flight_recorder.record_round(
                        "parrot_round", rounds=1,
                        program="parrot/round_step") as fr:
                    if self.n_buckets > 1:
                        # stratified on-device sampling (documented
                        # deviation from the reference's host
                        # np.random.seed(round) draws)
                        with fr.phase("device_compute"):
                            (self.global_vars, self.server_state,
                             rm) = self.bucketed_round_step(
                                self.device_data, self.global_vars,
                                self.server_state, sub)
                            if flight_recorder.enabled():
                                rm = jax.block_until_ready(rm)
                    else:
                        # host-side sampling stays outside the device
                        # phase — it lands in the host_gap residual
                        client_ids = jnp.asarray(
                            self._client_sampling(round_idx))
                        with fr.phase("device_compute"):
                            (self.global_vars, self.server_state,
                             rm) = self.round_step(
                                self.device_data, self.global_vars,
                                self.server_state, client_ids, sub)
                            if flight_recorder.enabled():
                                rm = jax.block_until_ready(rm)
                freq = int(getattr(self.args, "frequency_of_the_test", 5)
                           or 5)
                if round_idx % freq == 0 or round_idx == comm_rounds - 1:
                    out = self.eval_step(self.global_vars, test_batches)
                    n = max(float(out["n"]), 1.0)
                    final_metrics = self._record_metrics({
                        "test_loss": float(out["loss_sum"]) / n,
                        "test_acc": float(out["correct"]) / n,
                        "train_loss": float(rm["train_loss"]),
                        "round": round_idx,
                        "round_time": time.time() - t0,
                    }, f"parrot round {round_idx}")
                if ckpt is not None and (round_idx % ckpt_freq == 0
                                         or round_idx == comm_rounds - 1):
                    ckpt.save(round_idx, {
                        "round_idx": round_idx,
                        "global_vars": self.global_vars,
                        "server_state": self.server_state,
                    })
            # round boundary, outside the (old) mesh context: latch any
            # announced resize — checkpoint, re-mesh in place, ack
            self._maybe_resize(ckpt, round_idx)
        return final_metrics


    def _make_test_batches(self):
        x_te, y_te = self.test_global
        nb_te = max(1, -(-len(y_te) // self.bs))
        return make_batches(x_te, y_te, self.bs, nb_te,
                            self.bundle.input_dtype)

    def _record_metrics(self, metrics: Dict[str, Any], tag: str
                        ) -> Dict[str, Any]:
        self.metrics_history.append(metrics)
        mlops.log(metrics)
        logging.info("%s: %s", tag, metrics)
        return metrics

    def _train_fused(self) -> Dict[str, Any]:
        """``fused_rounds: true`` — run the scan-over-rounds fast path
        between eval points (~7x dispatch amortization through a remote
        accelerator).  Client sampling moves on-device (same distribution,
        different draws than the host path — documented deviation).
        Checkpoints (when ``checkpoint_dir`` is set) land at eval
        boundaries."""
        comm_rounds = int(self.args.comm_round)
        freq = int(getattr(self.args, "frequency_of_the_test", 5) or 5)
        test_batches = self._make_test_batches()
        rng = jax.random.PRNGKey(
            int(getattr(self.args, "random_seed", 0) or 0) + 23)
        final_metrics: Dict[str, Any] = {}
        done = 0

        ckpt = None
        ckpt_dir = getattr(self.args, "checkpoint_dir", None)
        if ckpt_dir:
            from ...utils.checkpoint import RoundCheckpointer

            ckpt = RoundCheckpointer(str(ckpt_dir))
            state = ckpt.restore()
            if state is not None:
                done = int(np.asarray(state["round_idx"])) + 1
                self.global_vars = state["global_vars"]
                if state.get("server_state"):
                    self.server_state = state["server_state"]
                logging.info("fused: resumed from round %d", done - 1)

        ctx = (self.mesh if self.mesh is not None
               else contextlib.nullcontext())
        with ctx:
            while done < comm_rounds:
                t0 = time.time()
                step = min(freq, comm_rounds - done)
                rng, sub = jax.random.split(rng)  # fresh stream per chunk
                rms = self.run_rounds_fused(step, rng=sub)
                done += step
                out = self.eval_step(self.global_vars, test_batches)
                n = max(float(out["n"]), 1.0)
                train_loss = np.asarray(rms["train_loss"])
                final_metrics = self._record_metrics({
                    "test_loss": float(out["loss_sum"]) / n,
                    "test_acc": float(out["correct"]) / n,
                    "train_loss": float(train_loss[-1]),
                    "round": done - 1,
                    "round_time": (time.time() - t0) / step,
                }, f"parrot fused rounds {done - step}-{done - 1}")
                if ckpt is not None:
                    ckpt.save(done - 1, {
                        "round_idx": done - 1,
                        "global_vars": self.global_vars,
                        "server_state": self.server_state,
                    })
        return final_metrics

