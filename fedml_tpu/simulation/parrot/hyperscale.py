"""Hyper-scale Parrot: streamed cohorts over virtual client populations.

``ParrotAPI`` keeps the whole dataset and a ``[N, cap]`` per-client
index matrix device-resident — the right call at 10²–10³ clients, a
dead end at 10⁵–10⁶ (the index matrix alone is gigabytes and every
client's padded slots live in HBM forever).  This module is the scale
path from ROADMAP item 1:

- **Streaming cohort pipeline** — each round's cohort grid is assembled
  on host from a :class:`~fedml_tpu.data.population.ClientPopulation`
  (lazy per-client row streams, nothing O(N·cap) materialized) and
  staged host→device with async ``jax.device_put``.  With
  ``stream_prefetch >= 2`` the staging is **double-buffered**: round
  ``r`` computes while round ``r+1``'s grid assembles and uploads, so
  the flight recorder's ``h2d`` phase collapses to the residual
  synchronization wait.  ``stream_prefetch <= 1`` is the sequential
  baseline (stage-then-compute) the overlap claim is measured against.
- **Client axis sharded across the mesh** — cohort grids carry the
  `grid_sharding` layout (client axis over every mesh axis, intra-batch
  fallback for small quotas), so a 4096-client cohort spreads over all
  chips/hosts and aggregation lowers to one all-reduce.
- **Hierarchical cohort sampling** — stratified size buckets (the
  shared `bucket_plan`) sampled per round by a counter-based RNG keyed
  on ``(run_id, seed, round)``: deterministic under crash-resume and
  never materializes per-client index matrices for the population.
  Optional availability traces (diurnal duty cycles) filter candidates
  before the draw.
- **Sharded per-client algorithm state** — SCAFFOLD variates / FedDyn
  lambdas live device-resident as ``[N_pad, ...]`` tables laid out
  along the client axis (`stacked_client_sharding`) and are
  gathered/scattered per cohort inside the round jit instead of held
  replicated per device.

Headline metric: **clients-simulated/sec** (`stream_stats()`), with the
h2d/compute overlap fraction read from the same flight-recorder phases
`fedml perf report` prints.
"""

from __future__ import annotations

import contextlib
import logging
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ...constants import AXIS_CLIENTS, FED_OPT_FEDDYN, FED_OPT_FEDOPT, \
    FED_OPT_MIME, FED_OPT_SCAFFOLD
from ...core import mlops
from ...core.mlops import flight_recorder, ledger
from ...data.population import ClientPopulation, load_population, \
    philox_generator
from ...ml.engine.local_update import build_eval_step, build_local_update, \
    make_batches
from ...ml.engine.mesh import build_hybrid_mesh, build_mesh
from ...ml.aggregator.robust import parse_robust_agg
from ...ml.engine.optimizers import build_server_optimizer
from ...ops import epilogue as _epilogue
from .parrot_api import _stack_zeros_like, _zeros_like, algo_in_axes, \
    bucket_plan, build_aggregate, grid_sharding, per_client_algo_state, \
    stacked_client_sharding

__all__ = [
    "HierarchicalCohortSampler",
    "StreamingParrotAPI",
    "make_availability",
]


def make_availability(spec: Optional[str], n_clients: int, seed: int = 0
                      ) -> Optional[Callable[[int, np.ndarray], np.ndarray]]:
    """Availability trace factory.

    ``None``/``"always"`` → no trace.  ``"diurnal:<duty>:<period>"`` →
    each client gets a deterministic phase offset in [0, 1) and is
    available at round ``r`` iff ``(r/period + phase) % 1 < duty`` — a
    rotating duty cycle approximating device-charging/idle windows
    (Parrot §3.2's trace-driven availability, reproduced synthetically
    so runs need no external trace files)."""
    if not spec or spec == "always":
        return None
    parts = str(spec).split(":")
    if parts[0] != "diurnal":
        raise ValueError(f"unknown availability trace {spec!r} "
                         "(supported: 'always', 'diurnal:<duty>:<period>')")
    duty = float(parts[1]) if len(parts) > 1 else 0.5
    period = float(parts[2]) if len(parts) > 2 else 24.0
    # one O(N) float32 vector — the only per-client state the trace keeps
    phases = philox_generator("avail_phase", seed, n_clients).random(
        n_clients, dtype=np.float32)

    def available(round_idx: int, ids: np.ndarray) -> np.ndarray:
        return ((round_idx / period + phases[ids]) % 1.0) < duty

    return available


class HierarchicalCohortSampler:
    """Stratified cohort sampling for populations of 10⁵–10⁶ clients.

    Strata come from the shared `bucket_plan` (equal-count size buckets
    with quotas summing to ``k``); each round draws every stratum's
    quota independently with a Philox generator keyed on
    ``(run_id, seed, round)``.  Determinism is per-round and positional
    — a crashed run that resumes at round ``r`` re-solicits the exact
    cohort round ``r`` would have had, with no sequential RNG state to
    replay.  The only O(N) state is the stratum membership arrays (a
    permutation of ``arange(N)``); no ``[N, cap]`` index matrices, no
    per-client objects."""

    def __init__(self, sizes: np.ndarray, k: int, bs: int,
                 n_buckets: int = 1, cap_ratio: float = 0.0,
                 run_id: str = "", seed: int = 0,
                 availability: Optional[Callable] = None) -> None:
        sizes = np.asarray(sizes)
        self.k = int(k)
        self.run_id = str(run_id)
        self.seed = int(seed)
        self.availability = availability
        plan = bucket_plan(sizes, k, bs, max(1, int(n_buckets)),
                           float(cap_ratio))
        self.strata = [{
            "members": np.asarray(b["members"], np.int64),
            "q": int(b["q"]),
            "nb": int(b["nb"]),
            "nb_full": int(b["nb_full"]),
        } for b in plan]

    def cohort(self, round_idx: int) -> List[Dict[str, np.ndarray]]:
        """Per-stratum ``{"ids", "starts"}`` draws for one round.

        ``starts`` seeds the rotating sample window of over-capacity
        clients (host-side analogue of `_gather_batches_windowed`'s
        on-device draw) — carried with the cohort so a resumed run
        reads the identical windows."""
        g = philox_generator("cohort", self.run_id, self.seed, round_idx)
        out = []
        for s in self.strata:
            members, q = s["members"], s["q"]
            pool = members
            if self.availability is not None:
                avail = members[self.availability(round_idx, members)]
                if len(avail) >= q:
                    pool = avail
                elif len(avail) > 0:
                    logging.warning(
                        "hyperscale sampler: stratum has %d available < "
                        "quota %d at round %d — over-soliciting the "
                        "available set", len(avail), q, round_idx)
                    pool = avail
            if len(pool) >= q:
                ids = pool[g.choice(len(pool), size=q, replace=False)]
            else:  # degenerate trace: fill the quota with replacement
                ids = pool[g.integers(0, len(pool), size=q)]
            starts = g.integers(0, 1 << 30, size=q, dtype=np.int64)
            out.append({"ids": np.asarray(ids, np.int64), "starts": starts,
                        "nb": s["nb"], "nb_full": s["nb_full"]})
        return out


class _Staged:
    """One round's cohort, in flight to the device."""

    __slots__ = ("grids", "weights", "ids", "cohort_ids", "nbytes",
                 "assemble_s")

    def __init__(self, grids, weights, ids, cohort_ids, nbytes, assemble_s):
        self.grids = grids          # tuple of {"x","y","mask"} device trees
        self.weights = weights      # tuple of [q_b] device arrays
        self.ids = ids              # tuple of [q_b] int32 device arrays
        self.cohort_ids = cohort_ids  # host np.ndarray (for logging/tests)
        self.nbytes = nbytes
        self.assemble_s = assemble_s


class StreamingParrotAPI:
    """Parrot rounds over a virtual population with streamed cohorts.

    Shares the round arithmetic with `ParrotAPI` (same `local_update`,
    `build_aggregate`, `per_client_algo_state`) — the difference is the
    data plane: cohort grids are host-assembled per round and streamed
    in, instead of gathered from a device-resident ``[N, cap]`` matrix.
    With ``cohort_sampling="reference"`` and one stratum the trajectory
    matches `ParrotAPI.train()` (same sampling draws, same rng stream,
    same vmap/aggregate graph) — the parity tests pin this.
    """

    def __init__(self, args: Any, device: Any, dataset: Optional[Tuple],
                 bundle: Any, population: Optional[ClientPopulation] = None,
                 use_mesh: bool = False) -> None:
        self.args = args
        self.bundle = bundle
        self.algo = str(getattr(args, "federated_optimizer", "FedAvg"))
        self.pop = population if population is not None \
            else load_population(args, dataset)
        self.n_total = self.pop.n_clients
        self.k = int(args.client_num_per_round)
        self.bs = int(getattr(args, "batch_size", 32))
        self.n_buckets = max(1, int(getattr(args, "hetero_buckets", 1) or 1))
        self.bucket_cap = float(
            getattr(args, "hetero_bucket_cap", 0.0) or 0.0)
        self.prefetch = int(getattr(args, "stream_prefetch", 2) or 2)
        seed = int(getattr(args, "random_seed", 0) or 0)

        # ---- host-resident base arrays (the ONLY copy of the data) ----
        store_dtype = bundle.input_dtype
        if str(getattr(args, "data_dtype", "") or "") == "bfloat16" \
                and bundle.input_dtype == jnp.float32:
            store_dtype = jnp.bfloat16
        self.x_base = np.asarray(self.pop.x, dtype=store_dtype)
        self.y_base = np.asarray(self.pop.y)

        # ---- mesh -----------------------------------------------------
        self.mesh = None
        if use_mesh:
            dcn = dict(getattr(args, "dcn_mesh_shape", None) or {})
            dcn_prod = int(np.prod(list(dcn.values()))) if dcn else 1
            shape = getattr(args, "mesh_shape", None) or {
                AXIS_CLIENTS: max(
                    min(len(jax.devices()) // dcn_prod, self.k), 1)}
            self.mesh = (build_hybrid_mesh(shape, dcn) if dcn
                         else build_mesh(shape))
        msize = 1 if self.mesh is None else int(
            np.prod([self.mesh.shape[n] for n in self.mesh.axis_names]))
        #: per-client state tables pad N to a multiple of the mesh so the
        #: client-axis layout is balanced (GSPMD would otherwise give one
        #: device the ragged shard)
        self.n_pad = -(-self.n_total // msize) * msize

        # ---- sampler --------------------------------------------------
        self.sampling = str(getattr(args, "cohort_sampling", "") or
                            ("reference" if self.n_buckets <= 1
                             else "hierarchical"))
        avail = make_availability(
            getattr(args, "availability_trace", None), self.n_total, seed)
        if self.sampling == "reference" and avail is not None:
            raise ValueError("availability traces need "
                             "cohort_sampling='hierarchical'")
        self.sampler = HierarchicalCohortSampler(
            self.pop.sizes, self.k, self.bs,
            n_buckets=self.n_buckets, cap_ratio=self.bucket_cap,
            run_id=str(getattr(args, "run_id", "") or ""), seed=seed,
            availability=avail)
        if self.sampling == "reference":
            # parity with ParrotAPI: ONE stratum at the global max
            # capacity, cohorts drawn with the reference host RNG
            nb = max(1, -(-int(self.pop.sizes.max()) // self.bs))
            self.sampler.strata = [{
                "members": np.arange(self.n_total, dtype=np.int64),
                "q": self.k, "nb": nb, "nb_full": nb}]

        # ---- model / engine (identical to ParrotAPI) ------------------
        rng = jax.random.PRNGKey(seed)
        self.global_vars = bundle.init_variables(
            rng, batch_size=min(self.bs, 8))
        self.local_update = build_local_update(bundle, args)
        self.eval_step = jax.jit(build_eval_step(bundle))

        # ---- server state: per-client tables sharded on the client axis
        self.server_state: Dict[str, Any] = {}
        state_shard = stacked_client_sharding(self.mesh)
        if self.algo == FED_OPT_FEDOPT:
            # same channel choice as build_aggregate: fused-epilogue
            # optimizer state when the server optimizer maps onto the
            # kernel family, optax state otherwise
            fused_opt = (_epilogue.spec_from_args(args)
                         if parse_robust_agg(
                             getattr(args, "robust_agg", None)) is None
                         else None)
            if fused_opt is not None:
                self.server_state["opt_state"] = _epilogue.init_opt_state(
                    self.global_vars["params"], fused_opt)
            else:
                self.server_tx = build_server_optimizer(args)
                self.server_state["opt_state"] = self.server_tx.init(
                    self.global_vars["params"])
        if self.algo == FED_OPT_SCAFFOLD:
            self.server_state["c_global"] = _zeros_like(
                self.global_vars["params"])
            self.server_state["c_locals"] = self._stacked_table(
                self.global_vars["params"], state_shard)
        if self.algo == FED_OPT_FEDDYN:
            self.server_state["h"] = _zeros_like(self.global_vars["params"])
            self.server_state["lambdas"] = self._stacked_table(
                self.global_vars["params"], state_shard)
        if self.algo == FED_OPT_MIME:
            self.server_state["momentum"] = _zeros_like(
                self.global_vars["params"])

        self._shardings = [grid_sharding(self.mesh, s["q"], self.bs)
                           for s in self.sampler.strata]
        self.round_step_fn = self._build_round_step()
        self.round_step = jax.jit(self.round_step_fn,
                                  donate_argnums=(3, 4))
        self.metrics_history: List[Dict[str, Any]] = []
        self._reset_stats()

    # ------------------------------------------------------------------
    def _stacked_table(self, template, sharding):
        table = _stack_zeros_like(template, self.n_pad)
        return jax.device_put(table, sharding) if sharding is not None \
            else table

    def _reset_stats(self) -> None:
        self._h2d_s = 0.0
        self._compute_s = 0.0
        self._assemble_s = 0.0
        self._bytes_h2d = 0
        self._clients_done = 0
        self._wall_s = 0.0

    # ------------------------------------------------------------------
    def _cohort(self, round_idx: int) -> List[Dict[str, np.ndarray]]:
        if self.sampling == "reference":
            s = self.sampler.strata[0]
            if self.n_total == self.k:
                ids = np.arange(self.k, dtype=np.int64)
            else:
                np.random.seed(round_idx)  # ParrotAPI._client_sampling
                ids = np.random.choice(self.n_total, self.k,
                                       replace=False).astype(np.int64)
            return [{"ids": ids,
                     "starts": np.zeros(self.k, np.int64),
                     "nb": s["nb"], "nb_full": s["nb_full"]}]
        return self.sampler.cohort(round_idx)

    def _assemble(self, sl: Dict[str, Any]) -> Dict[str, np.ndarray]:
        """Host gather: one stratum's cohort → a padded [q, nb, bs, ...]
        batch grid.  Over-capacity clients contribute the rotating
        circular window seeded by the sampler; everyone else their
        padded slots (-1 masks the tail) — mirrors the device gather of
        `_gather_batches(_windowed)` exactly, one cohort at a time."""
        ids, starts = sl["ids"], sl["starts"]
        nb = int(sl["nb"])
        capn = nb * self.bs
        q = len(ids)
        idx = np.full((q, capn), -1, np.int64)
        for j, cid in enumerate(ids):
            rows = self.pop.rows(int(cid))
            n_i = len(rows)
            if n_i > capn:
                pos = (int(starts[j]) % n_i + np.arange(capn)) % n_i
                idx[j] = rows[pos]
            else:
                idx[j, :n_i] = rows[:n_i]
        safe = np.maximum(idx, 0).reshape(-1)
        x = self.x_base[safe].reshape(
            (q, nb, self.bs) + self.x_base.shape[1:])
        y = self.y_base[safe].reshape(
            (q, nb, self.bs) + self.y_base.shape[1:])
        mask = (idx >= 0).astype(np.float32).reshape(q, nb, self.bs)
        return {"x": x, "y": y, "mask": mask}

    def _stage(self, round_idx: int) -> _Staged:
        """Assemble round ``round_idx``'s cohort and start its upload.

        ``jax.device_put`` is async — the copy proceeds while the caller
        keeps dispatching; the consumer pays only the residual wait in
        its ``h2d`` phase.  Under double-buffering this is called right
        after round ``r``'s compute is dispatched, so assembly and
        upload hide behind device work."""
        t0 = time.perf_counter()
        cohort = self._cohort(round_idx)
        grids, weights, ids_dev, nbytes = [], [], [], 0
        for i, sl in enumerate(cohort):
            grid = self._assemble(sl)
            sh = self._shardings[i]
            dev = (jax.device_put(grid, sh) if sh is not None
                   else jax.device_put(grid))
            grids.append(dev)
            w = self.pop.sizes[sl["ids"]].astype(np.float32)
            weights.append(jax.device_put(w))
            ids_dev.append(jax.device_put(sl["ids"].astype(np.int32)))
            nbytes += sum(int(a.nbytes) for a in grid.values()) + w.nbytes
        if flight_recorder.enabled():
            flight_recorder.note_transfer("h2d", nbytes)
        self._bytes_h2d += nbytes
        cohort_ids = np.concatenate([sl["ids"] for sl in cohort])
        ledger.event("hyperscale", "stage", round_idx=int(round_idx),
                     clients=int(cohort_ids.size), nbytes=int(nbytes))
        return _Staged(tuple(grids), tuple(weights), tuple(ids_dev),
                       cohort_ids, nbytes, time.perf_counter() - t0)

    # ------------------------------------------------------------------
    def _build_round_step(self):
        """The streamed round jit: per-stratum vmapped local updates over
        grids that arrive as EXPLICIT traced arguments (already sharded
        by `_stage`), concatenated into the shared aggregation.  Same
        contract as `ParrotAPI._build_bucketed_round_step`, minus the
        on-device sampling/gather — sampling moved to the host sampler
        and the gather to `_assemble`."""
        in_axes = algo_in_axes(self.algo)
        aggregate = build_aggregate(self.args, self.algo, self.n_total,
                                    server_tx=getattr(self, "server_tx",
                                                      None))
        algo = self.algo
        local_update = self.local_update
        n_strata = len(self.sampler.strata)
        shardings = self._shardings

        def round_step(grids, weights, client_ids, global_vars,
                       server_state, rng):
            outs = []
            # single stratum consumes rng exactly like ParrotAPI's
            # uniform round (split to K client keys) — bit parity
            keys = ([rng] if n_strata == 1
                    else list(jax.random.split(rng, n_strata)))
            for i in range(n_strata):
                grid = grids[i]
                if shardings[i] is not None:
                    grid = jax.lax.with_sharding_constraint(
                        grid, shardings[i])
                ids = client_ids[i]
                rngs = jax.random.split(keys[i], ids.shape[0])
                algo_state = per_client_algo_state(algo, server_state, ids)
                new_vars, algo_out, metrics = jax.vmap(
                    local_update, in_axes=(None, 0, 0, in_axes))(
                        global_vars, grid, rngs, algo_state or None)
                outs.append((new_vars, algo_out, metrics, weights[i], ids))

            def cat(trees):
                return jax.tree_util.tree_map(
                    lambda *xs: jnp.concatenate(xs, axis=0), *trees)

            new_vars = cat([o[0] for o in outs])
            algo_out = cat([o[1] for o in outs])
            metrics = cat([o[2] for o in outs])
            all_w = jnp.concatenate([o[3] for o in outs])
            all_ids = jnp.concatenate([o[4] for o in outs])
            return aggregate(global_vars, server_state, all_ids,
                             new_vars, algo_out, metrics, all_w)

        return round_step

    # ------------------------------------------------------------------
    def train(self) -> Dict[str, Any]:
        comm_rounds = int(self.args.comm_round)
        seed = int(getattr(self.args, "random_seed", 0) or 0)
        rng = jax.random.PRNGKey(seed + 17)  # ParrotAPI.train's stream
        test_batches = self._make_test_batches()
        final_metrics: Dict[str, Any] = {}
        streaming = self.prefetch >= 2
        self._reset_stats()

        ckpt = None
        start_round = 0
        ckpt_dir = getattr(self.args, "checkpoint_dir", None)
        ckpt_freq = int(getattr(self.args, "checkpoint_frequency", 10) or 10)
        if ckpt_dir:
            from ...utils.checkpoint import RoundCheckpointer

            ckpt = RoundCheckpointer(str(ckpt_dir))
            state = ckpt.restore()
            if state is not None:
                start_round = int(np.asarray(state["round_idx"])) + 1
                self.global_vars = state["global_vars"]
                if state.get("server_state"):
                    self.server_state = state["server_state"]
                # replay the rng stream to the resume point so the
                # cohort AND client-key draws match the unbroken run
                for _ in range(start_round):
                    rng, _ = jax.random.split(rng)
                logging.info("hyperscale: resumed from round %d",
                             start_round - 1)

        t_wall = time.perf_counter()
        ctx = (self.mesh if self.mesh is not None
               else contextlib.nullcontext())
        staged: Optional[_Staged] = None
        with ctx:
            if streaming:
                staged = self._stage(start_round)
                self._assemble_s += staged.assemble_s
            for round_idx in range(start_round, comm_rounds):
                t0 = time.time()
                rng, sub = jax.random.split(rng)
                with flight_recorder.record_round(
                        "hyperscale_round", rounds=1,
                        program="parrot/streaming_round_step") as fr:
                    if streaming:
                        th = time.perf_counter()
                        with fr.phase("h2d"):
                            # residual wait only: the upload started
                            # last round, behind the device compute
                            jax.block_until_ready(staged.grids)
                        self._h2d_s += time.perf_counter() - th
                        (self.global_vars, self.server_state,
                         rm) = self.round_step(
                            staged.grids, staged.weights, staged.ids,
                            self.global_vars, self.server_state, sub)
                        # round r+1 assembles + uploads WHILE the device
                        # runs round r — the double buffer
                        nxt = None
                        if round_idx + 1 < comm_rounds:
                            nxt = self._stage(round_idx + 1)
                            self._assemble_s += nxt.assemble_s
                        tc = time.perf_counter()
                        with fr.phase("device_compute"):
                            rm = jax.block_until_ready(rm)
                        self._compute_s += time.perf_counter() - tc
                        staged = nxt
                    else:
                        th = time.perf_counter()
                        with fr.phase("h2d"):
                            cur = self._stage(round_idx)
                            self._assemble_s += cur.assemble_s
                            jax.block_until_ready(cur.grids)
                        self._h2d_s += time.perf_counter() - th
                        tc = time.perf_counter()
                        with fr.phase("device_compute"):
                            (self.global_vars, self.server_state,
                             rm) = self.round_step(
                                cur.grids, cur.weights, cur.ids,
                                self.global_vars, self.server_state, sub)
                            rm = jax.block_until_ready(rm)
                        self._compute_s += time.perf_counter() - tc
                self._clients_done += self.k
                freq = int(getattr(self.args, "frequency_of_the_test", 5)
                           or 5)
                if round_idx % freq == 0 or round_idx == comm_rounds - 1:
                    out = self.eval_step(self.global_vars, test_batches)
                    n = max(float(out["n"]), 1.0)
                    final_metrics = self._record_metrics({
                        "test_loss": float(out["loss_sum"]) / n,
                        "test_acc": float(out["correct"]) / n,
                        "train_loss": float(rm["train_loss"]),
                        "round": round_idx,
                        "round_time": time.time() - t0,
                    }, f"hyperscale round {round_idx}")
                if ckpt is not None and (round_idx % ckpt_freq == 0
                                         or round_idx == comm_rounds - 1):
                    ckpt.save(round_idx, {
                        "round_idx": round_idx,
                        "global_vars": self.global_vars,
                        "server_state": self.server_state,
                    })
        self._wall_s = time.perf_counter() - t_wall
        return final_metrics

    # ------------------------------------------------------------------
    def stream_stats(self) -> Dict[str, Any]:
        """The headline: clients-simulated/sec, plus the h2d/compute
        decomposition the overlap claim is made from.  ``h2d_share`` is
        the fraction of wall time spent BLOCKED on staging — under
        double-buffering it collapses toward 0 because the upload hides
        behind the previous round's compute; ``overlap_frac`` is the
        share of staging work hidden that way."""
        wall = max(self._wall_s, 1e-9)
        stage_total = self._assemble_s
        hidden = max(0.0, stage_total - self._h2d_s)
        return {
            "n_clients": self.n_total,
            "clients_simulated": self._clients_done,
            "clients_per_sec": round(self._clients_done / wall, 2),
            "wall_s": round(wall, 4),
            "h2d_blocked_s": round(self._h2d_s, 4),
            "h2d_share": round(self._h2d_s / wall, 4),
            "compute_s": round(self._compute_s, 4),
            "compute_share": round(self._compute_s / wall, 4),
            "stage_work_s": round(stage_total, 4),
            "overlap_frac": round(hidden / max(stage_total, 1e-9), 4),
            "h2d_bytes": int(self._bytes_h2d),
            "prefetch": self.prefetch,
            "sampling": self.sampling,
            "strata": len(self.sampler.strata),
        }

    def _make_test_batches(self):
        x_te, y_te = self.pop.test
        nb_te = max(1, -(-len(y_te) // self.bs))
        return make_batches(x_te, y_te, self.bs, nb_te,
                            self.bundle.input_dtype)

    def _record_metrics(self, metrics: Dict[str, Any], tag: str
                        ) -> Dict[str, Any]:
        self.metrics_history.append(metrics)
        mlops.log(metrics)
        logging.info("%s: %s", tag, metrics)
        return metrics
